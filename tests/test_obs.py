"""kubernetes_tpu/obs — the scheduling trace layer: span tracer, per-pod
decision journal (with per-plugin attribution from the solve tensors),
flight recorder, explain CLI, debug endpoints, and the structured
logging satellite."""

import json
import logging

import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.api.wrappers import MakeNode, MakePod
from kubernetes_tpu.obs import (
    ObsConfig,
    FlightRecorder,
    PodDecisionJournal,
    Tracer,
    build_obs,
    explain_pod,
    parse_stream,
    validate_line,
    validate_lines,
)
from kubernetes_tpu.scheduler import Scheduler, SchedulerConfig
from kubernetes_tpu.solver.exact import ExactSolverConfig
from kubernetes_tpu.state.cluster import ClusterState
from kubernetes_tpu.utils.clock import FakeClock
from kubernetes_tpu.utils.logging import JsonLineFormatter, setup


def mk_cluster(n_nodes=3, cpu="4", mem="8Gi"):
    cs = ClusterState()
    for i in range(n_nodes):
        cs.create_node(
            MakeNode()
            .name(f"node-{i}")
            .capacity({"cpu": cpu, "memory": mem, "pods": "20"})
            .obj()
        )
    return cs


def obs_scheduler(cs, **obs_kw):
    cfg = SchedulerConfig(
        batch_size=64,
        solver=ExactSolverConfig(tie_break="first"),
        obs=ObsConfig(spans=True, journal=True, **obs_kw),
    )
    return Scheduler(cs, cfg)


# -- span tracer --------------------------------------------------------


class TestTracer:
    def test_disabled_tracer_is_noop(self):
        rec = FlightRecorder()
        tr = Tracer(enabled=False, recorder=rec)
        with tr.span("anything", a=1) as sp:
            sp.set(b=2)  # absorbed
        assert rec.spans() == []
        assert tr.current() is None

    def test_nesting_links_parent_and_trace(self):
        rec = FlightRecorder()
        tr = Tracer(clock=FakeClock(), enabled=True, recorder=rec)
        tr.trace_id = 7
        with tr.span("outer") as outer:
            with tr.span("inner") as inner:
                assert tr.current() is inner
            assert tr.current() is outer
        spans = rec.spans()  # finish order: inner first
        assert [s["name"] for s in spans] == ["inner", "outer"]
        inner_d, outer_d = spans
        assert inner_d["parent"] == outer_d["span"]
        assert inner_d["trace"] == outer_d["trace"] == 7
        assert outer_d["parent"] is None

    def test_exception_marks_error_status(self):
        rec = FlightRecorder()
        tr = Tracer(clock=FakeClock(), enabled=True, recorder=rec)
        with pytest.raises(ValueError):
            with tr.span("boom"):
                raise ValueError("x")
        (sp,) = rec.spans()
        assert sp["status"] == "error"
        assert sp["attrs"]["error"] == "ValueError"

    def test_virtual_time_durations(self):
        clock = FakeClock()
        rec = FlightRecorder()
        tr = Tracer(clock=clock, enabled=True, recorder=rec)
        with tr.span("timed"):
            clock.advance(2.5)
        (sp,) = rec.spans()
        assert sp["dur"] == 2.5
        assert sp["end"] - sp["start"] == 2.5


# -- flight recorder ----------------------------------------------------


class TestFlightRecorder:
    def test_ring_is_bounded_and_counts_drops(self):
        rec = FlightRecorder(span_capacity=2, decision_capacity=2)
        for i in range(5):
            rec.record_decision({"k": "dec", "i": i})
        assert len(rec.decisions()) == 2
        assert [d["i"] for d in rec.decisions()] == [3, 4]
        assert rec.dropped_decisions == 3

    def test_dump_roundtrip(self, tmp_path):
        rec = FlightRecorder(dump_path=str(tmp_path / "dump.jsonl"))
        rec.record_decision({"k": "dec", "pod": "ns/p"})
        path = rec.dump(trigger="manual")
        assert path == str(tmp_path / "dump.jsonl")
        lines = (tmp_path / "dump.jsonl").read_text().splitlines()
        assert json.loads(lines[0])["pod"] == "ns/p"

    def test_dump_without_target_is_none_but_counted(self):
        rec = FlightRecorder()
        before = metrics.flight_recorder_dumps_total.labels(
            "manual"
        )._value.get()
        assert rec.dump() is None
        after = metrics.flight_recorder_dumps_total.labels(
            "manual"
        )._value.get()
        assert after == before + 1


# -- journal schema -----------------------------------------------------


class TestJournalSchema:
    def test_record_shape_and_validation(self):
        j = PodDecisionJournal(clock=FakeClock(5.0))
        pod = MakePod().name("p").uid("u1").obj()
        j.record(3, 9, pod, "bound", node="n1", attempts=2)
        assert validate_lines(j.lines) == []
        rec = json.loads(j.lines[0])
        assert rec == {
            "k": "dec", "v": 1, "step": 3, "cycle": 9,
            "pod": "default/p", "uid": "u1", "outcome": "bound",
            "t": 5.0, "node": "n1", "attempts": 2,
            "trace": "s-1:3:default/p",
        }

    @pytest.mark.parametrize(
        "line,frag",
        [
            ("not json", "not JSON"),
            ('{"k":"mystery"}', "unknown record kind"),
            ('{"k":"dec","v":1}', "missing"),
            (
                '{"k":"dec","v":99,"step":1,"cycle":1,"pod":"a/b",'
                '"outcome":"bound","t":0}',
                "unsupported schema version",
            ),
            (
                '{"k":"dec","v":1,"step":1,"cycle":1,"pod":"a/b",'
                '"outcome":"levitated","t":0}',
                "unknown outcome",
            ),
            (
                '{"k":"dec","v":1,"step":1,"cycle":1,"pod":"a/b",'
                '"outcome":"bound","t":0,"plugins":{"Fit":[1]}}',
                "not [rejected, of]",
            ),
        ],
    )
    def test_validate_rejects(self, line, frag):
        err = validate_line(line)
        assert err is not None and frag in err


# -- scheduler integration ---------------------------------------------


class TestSchedulerJournal:
    def test_bound_and_unschedulable_with_attribution(self):
        cs = mk_cluster(3)
        sched = obs_scheduler(cs)
        for i in range(3):
            cs.create_pod(
                MakePod().name(f"ok{i}").uid(f"u{i}").req({"cpu": "100m"}).obj()
            )
        # resource-infeasible on every node
        cs.create_pod(
            MakePod().name("huge").uid("u-huge").req({"cpu": "64"}).obj()
        )
        # statically infeasible (selector matches no node)
        cs.create_pod(
            MakePod()
            .name("selector")
            .uid("u-sel")
            .req({"cpu": "100m"})
            .node_selector({"zone": "nowhere"})
            .obj()
        )
        sched.run_until_settled()
        assert validate_lines(sched.journal.lines) == []
        last = sched.journal.last_outcomes()
        for i in range(3):
            assert last[f"default/ok{i}"]["outcome"] == "bound"
            assert last[f"default/ok{i}"]["node"]
        huge = last["default/huge"]
        assert huge["outcome"] == "unschedulable"
        assert huge["plugins"]["NodeResourcesFit"] == [3, 3]
        assert "Insufficient cpu" in huge["reason"]
        sel = last["default/selector"]
        assert sel["outcome"] == "unschedulable"
        # the fused static family reports under its dominant member
        assert sel["plugins"]["NodeAffinity"] == [3, 3]

    def test_spans_cover_the_loop_stages(self):
        cs = mk_cluster(2)
        sched = obs_scheduler(cs)
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        spans = sched.flight.spans()
        names = {s["name"] for s in spans}
        assert {
            "schedule_batch", "pop", "snapshot", "tensorize", "fold",
            "dispatch", "apply", "bind", "enqueue",
        } <= names
        # every stage span of batch 1 shares the root's trace id
        root = next(s for s in spans if s["name"] == "schedule_batch")
        assert root["trace"] == 1
        for stage in ("pop", "snapshot", "tensorize", "dispatch", "apply"):
            sp = next(s for s in spans if s["name"] == stage)
            assert sp["trace"] == root["trace"]

    def test_trace_step_initialized_and_shared(self):
        cs = mk_cluster(1)
        sched = obs_scheduler(cs)
        assert sched._trace_step == 0  # satellite: no getattr conjuring
        sched.schedule_batch()  # idle cycle still numbers
        assert sched._trace_step == 1
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        recs = [json.loads(ln) for ln in sched.journal.lines]
        assert recs and all(r["step"] >= 2 for r in recs)

    def test_pipelined_records_attribute_to_their_batch(self):
        """Commit-time journal records and bind spans must carry the
        step of the batch whose SOLVE approved them — in the pipelined
        loop batch k's bindings commit after batch k+1's step increment,
        so reading the live counter would misattribute them."""
        cs = mk_cluster(2, cpu="16")
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=2,
                solver=ExactSolverConfig(tie_break="first"),
                # full-fidelity spans: this test asserts EVERY bind
                # span's attribution (sampling is covered separately)
                obs=ObsConfig(
                    spans=True, journal=True, bind_span_sample_n=1
                ),
            ),
        )
        for i in range(5):
            cs.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "100m"}).obj()
            )
        sched.run_pipelined()
        last = sched.journal.last_outcomes()
        steps = sorted(r["step"] for r in last.values())
        # p0,p1 solved in batch 1; p2,p3 in batch 2; p4 in batch 3 —
        # even though batch 1's binds commit after batch 2 dispatched
        assert steps == [1, 1, 2, 2, 3]
        spans = sched.flight.spans()
        # pipelined mode has no root span: stage spans still must carry
        # their batch's trace id, never the 0 default
        for name in ("tensorize", "snapshot", "dispatch", "apply", "bind"):
            stage = [s for s in spans if s["name"] == name]
            assert stage, name
            assert all(s["trace"] >= 1 for s in stage), name
        bind_traces = sorted(
            s["trace"] for s in spans if s["name"] == "bind"
        )
        assert bind_traces == [1, 1, 2, 2, 3]

    def test_disabled_obs_leaves_no_artifacts(self):
        cs = mk_cluster(1)
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=8, solver=ExactSolverConfig(tie_break="first")
            ),
        )
        assert sched.journal is None and sched.flight is None
        assert not sched.obs.enabled
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        assert all(p.node_name for p in cs.list_pods())

    def test_journal_streams_to_file(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        cs = mk_cluster(2)
        sched = obs_scheduler(cs, journal_path=str(path))
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        lines = path.read_text().splitlines()
        assert lines == sched.journal.lines


# -- pending_pods gauge satellite --------------------------------------


def _gauge(queue):
    return metrics.pending_pods.labels(queue)._value.get()


class TestPendingGauge:
    def test_refreshes_on_queue_transitions_and_idle_cycles(self):
        cs = mk_cluster(2)
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=8, solver=ExactSolverConfig(tie_break="first")
            ),
        )
        cs.create_pod(MakePod().name("a").req({"cpu": "100m"}).obj())
        cs.create_pod(MakePod().name("b").req({"cpu": "100m"}).obj())
        # the watch-ingest path refreshed the gauge — no cycle ran yet
        assert _gauge("active") == 2
        sched.run_until_settled()
        assert _gauge("active") == 0
        # gated pods surface too (queue-only transition)
        cs.create_pod(
            MakePod()
            .name("gated")
            .req({"cpu": "100m"})
            .scheduling_gates(["wait"])
            .obj()
        )
        assert _gauge("gated") == 1
        # an idle/empty cycle keeps it fresh rather than erroring stale
        sched.schedule_batch()
        assert _gauge("gated") == 1
        cs.delete_pod("default", "gated")
        assert _gauge("gated") == 0


# -- explain ------------------------------------------------------------


class TestExplain:
    def _journaled_scheduler(self):
        cs = mk_cluster(2)
        sched = obs_scheduler(cs)
        cs.create_pod(
            MakePod().name("win").uid("u-win").req({"cpu": "100m"}).obj()
        )
        cs.create_pod(
            MakePod().name("lose").uid("u-lose").req({"cpu": "64"}).obj()
        )
        sched.run_until_settled()
        return sched

    def test_explain_matches_by_uid_key_and_name(self):
        sched = self._journaled_scheduler()
        dec, spans = parse_stream(sched.flight.lines())
        for ref in ("u-lose", "default/lose", "lose"):
            out = explain_pod(dec, ref, spans=spans)
            assert out.found, ref
            assert out.terminal["outcome"] == "unschedulable"
        text = explain_pod(dec, "u-lose", spans=spans).render()
        assert "NodeResourcesFit rejected 2/2 nodes" in text
        assert "terminal outcome: unschedulable" in text
        bound = explain_pod(dec, "u-win").render()
        assert "terminal outcome: bound to node-" in bound

    def test_explain_unknown_pod(self):
        sched = self._journaled_scheduler()
        dec, _ = parse_stream(sched.flight.lines())
        out = explain_pod(dec, "nope")
        assert not out.found
        assert "no journal records" in out.render()

    def test_cli_explain_and_validate(self, tmp_path, capsys):
        from kubernetes_tpu.obs.__main__ import main

        sched = self._journaled_scheduler()
        path = tmp_path / "journal.jsonl"
        sched.journal.dump(path)
        assert main(["validate", str(path)]) == 0
        assert "schema OK" in capsys.readouterr().out
        assert main(["explain", "default/lose", "--trace", str(path)]) == 0
        out = capsys.readouterr().out
        assert "unschedulable" in out and "NodeResourcesFit" in out
        assert main(["explain", "ghost", "--trace", str(path)]) == 1
        bad = tmp_path / "bad.jsonl"
        bad.write_text('{"k":"dec"}\n')
        assert main(["validate", str(bad)]) == 1


# -- flight recorder triggers ------------------------------------------


class TestCrashDump:
    def test_cycle_crash_dumps_ring(self, tmp_path, monkeypatch):
        path = tmp_path / "crash.jsonl"
        cs = mk_cluster(2)
        sched = obs_scheduler(cs, dump_path=str(path))
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())

        def boom(*a, **kw):
            raise RuntimeError("induced")

        monkeypatch.setattr(sched, "_run_groups", boom)
        with pytest.raises(RuntimeError):
            sched.schedule_batch()
        assert path.exists()
        # pop-phase spans of the dying batch made it into the dump
        kinds = {json.loads(ln)["k"] for ln in path.read_text().splitlines()}
        assert "span" in kinds


# -- debug endpoints ----------------------------------------------------


class TestDebugEndpoints:
    def test_flightrecorder_and_spans_routes(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from kubernetes_tpu.server.extender import ExtenderCore, make_app

        cs = mk_cluster(2)
        sched = obs_scheduler(cs)
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        core = ExtenderCore(cs, backend="oracle", tracer=sched.obs)
        app = make_app(core, recorder=sched.flight)

        async def drive():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/debug/flightrecorder")
                assert r.status == 200
                doc = await r.json()
                assert doc["decisions"] and doc["spans"]
                outcomes = {d["outcome"] for d in doc["decisions"]}
                assert "bound" in outcomes
                r = await client.get("/debug/spans")
                assert r.status == 200
                names = {s["name"] for s in (await r.json())["spans"]}
                assert "schedule_batch" in names
            finally:
                await client.close()

        asyncio.new_event_loop().run_until_complete(drive())

    def test_endpoints_404_when_disabled(self):
        import asyncio

        from aiohttp.test_utils import TestClient, TestServer

        from kubernetes_tpu.server.extender import ExtenderCore, make_app

        cs = mk_cluster(1)
        app = make_app(ExtenderCore(cs, backend="oracle"))

        async def drive():
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                for route in ("/debug/flightrecorder", "/debug/spans"):
                    r = await client.get(route)
                    assert r.status == 404
            finally:
                await client.close()

        asyncio.new_event_loop().run_until_complete(drive())


# -- structured logging satellite --------------------------------------


class TestStructuredLogging:
    def test_json_formatter_carries_extras(self):
        fmt = JsonLineFormatter()
        rec = logging.LogRecord(
            "kubernetes_tpu.scheduler", logging.INFO, __file__, 1,
            "bound %d pods", (3,), None,
        )
        rec.step = 12
        rec.pod = "default/p"
        out = json.loads(fmt.format(rec))
        assert out["msg"] == "bound 3 pods"
        assert out["step"] == 12 and out["pod"] == "default/p"
        assert out["level"] == "INFO"

    def test_setup_is_idempotent(self):
        logger = setup("json", logger_name="kubernetes_tpu.test_obs")
        setup("text", logger_name="kubernetes_tpu.test_obs")
        named = [
            h
            for h in logger.handlers
            if h.get_name() == "kubernetes_tpu.test_obs.structured"
        ]
        assert len(named) == 1

    def test_setup_rejects_unknown_format(self):
        with pytest.raises(ValueError):
            setup("xml")


# -- sim contract -------------------------------------------------------


class TestSimJournal:
    def test_same_seed_byte_identical_journal_and_completeness(self):
        from kubernetes_tpu.sim.harness import run_sim

        r1 = run_sim("churn_heavy", seed=3, cycles=3)
        r2 = run_sim("churn_heavy", seed=3, cycles=3)
        assert r1.ok and r2.ok  # includes the journal invariant
        assert r1.journal_lines == r2.journal_lines
        assert r1.journal_lines, "sim journaling must be on"
        assert validate_lines(r1.journal_lines) == []
        assert r1.summary["journal_digest"] == r2.summary["journal_digest"]

    def test_invariant_violation_dumps_flight_recorder(self, tmp_path):
        from kubernetes_tpu.sim.harness import SimHarness
        from kubernetes_tpu.sim.invariants import _record

        dump = tmp_path / "flight.jsonl"
        h = SimHarness(
            "churn_heavy", seed=1, cycles=2, flight_dump=str(dump)
        )
        # inject a fake violation at finish time: the dump must fire
        _record(h.violations, "capacity", 0, "synthetic for the test")
        res = h.run()
        assert res.flight_dump == str(dump)
        assert dump.exists()


def test_build_obs_disabled_returns_nones():
    tracer, journal, recorder = build_obs(None)
    assert not tracer.enabled and journal is None and recorder is None
    tracer2, journal2, recorder2 = build_obs(ObsConfig())
    assert not tracer2.enabled and journal2 is None and recorder2 is None


# -- schema catch-up (every field/outcome added since PR 3) -------------


class TestSchemaCatchup:
    def _base(self, **over):
        rec = {
            "k": "dec", "v": 1, "step": 1, "cycle": 1, "pod": "a/b",
            "outcome": "bound", "t": 0.0,
        }
        rec.update(over)
        return json.dumps(rec)

    def test_accepts_every_current_writer_field(self):
        line = self._base(
            uid="u", node="n1", reason="r", profile="default-scheduler",
            nominated="n2", replica="r0", trace="r0-1:3:a/b",
            attempts=2, incarnation=2, drain_chunk=4, drain_trace=17,
            plugins={"NodeResourcesFit": [1, 3]},
        )
        assert validate_line(line) is None

    def test_accepts_every_outcome_added_since_pr3(self):
        for outcome in (
            "solver_error", "quarantined", "recovered",
            "evicted_for_rebalance",
        ):
            assert validate_line(self._base(outcome=outcome)) is None

    @pytest.mark.parametrize(
        "over,frag",
        [
            # unknown-field strictness: writer drift fails validate
            ({"mystery_field": 1}, "unknown field"),
            # tag typing: the fleet/restart/drain tags added since PR 3
            ({"replica": 7}, "expected str"),
            ({"incarnation": "two"}, "expected int"),
            ({"incarnation": True}, "bool, expected int"),
            ({"drain_chunk": "4"}, "expected int"),
            ({"drain_trace": 1.5}, "expected int"),
            ({"trace": 12}, "expected str"),
            ({"attempts": "many"}, "expected int"),
            ({"step": 1.5}, "not an integer"),
            ({"t": "now"}, "not a number"),
            ({"pod": 9}, "not a string"),
        ],
    )
    def test_known_bad_fixtures_fail(self, over, frag):
        err = validate_line(self._base(**over))
        assert err is not None and frag in err, (over, err)

    def test_span_strictness_and_tuning_span_shape(self):
        # a tuning span as the runtime emits it: accepted
        span = json.dumps({
            "k": "span", "v": 1, "name": "tuning", "span": 3,
            "trace": 9, "parent": 1, "start": 0.0, "end": 1.0,
            "dur": 1.0, "status": "ok",
            "attrs": {"knob": "stream_depth", "decision": "probe"},
        })
        assert validate_line(span) is None
        bad_attr = json.dumps({
            "k": "span", "name": "tuning", "span": 3, "trace": 9,
            "start": 0.0, "end": 1.0, "dur": 1.0, "attrs": "knob",
        })
        assert "attrs is not an object" in validate_line(bad_attr)
        unknown = json.dumps({
            "k": "span", "name": "x", "span": 1, "trace": 1,
            "start": 0.0, "end": 0.0, "dur": 0.0, "surprise": 1,
        })
        assert "unknown field" in validate_line(unknown)
        bad_status = json.dumps({
            "k": "span", "name": "x", "span": 1, "trace": 1,
            "start": 0.0, "end": 0.0, "dur": 0.0, "status": "meh",
        })
        assert "not ok|error" in validate_line(bad_status)

    def test_live_scheduler_output_validates_clean(self):
        """The self-consistency half of the drift gate: everything the
        CURRENT writers emit (incl. trace ids) passes the strict
        validator — so tightening the validator without updating it
        for a new field is caught from both directions."""
        cs = mk_cluster(2)
        sched = obs_scheduler(cs)
        for i in range(3):
            cs.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "100m"}).obj()
            )
        cs.create_pod(MakePod().name("huge").req({"cpu": "64"}).obj())
        sched.run_until_settled()
        assert validate_lines(sched.journal.lines) == []
        # span lines from the flight recorder validate too
        assert validate_lines(sched.flight.lines()) == []


# -- flight-recorder coverage of the streaming loop + drain -------------


class TestStreamingFlightDump:
    def test_streaming_crash_dumps_ring(self, tmp_path, monkeypatch):
        path = tmp_path / "stream_crash.jsonl"
        cs = mk_cluster(2)
        sched = obs_scheduler(cs, dump_path=str(path))
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())

        def boom(*a, **kw):
            raise RuntimeError("induced streaming death")

        # die inside the streaming loop's apply path (an escaping
        # exception, not a solver fault the ladder would absorb)
        monkeypatch.setattr(sched, "_apply_flight", boom)
        with pytest.raises(RuntimeError):
            sched.run_streaming(max_batches=4)
        assert path.exists()
        kinds = {json.loads(ln)["k"] for ln in path.read_text().splitlines()}
        assert "span" in kinds

    def test_drain_planning_crash_dumps_ring(self, tmp_path):
        """drain_backlog's PRE-dispatch path (budget planning) dies
        before run_streaming's own crash handler could fire — the
        drain must dump the ring itself."""
        from kubernetes_tpu.solver.budget import BudgetExceeded

        path = tmp_path / "drain_crash.jsonl"
        cs = mk_cluster(2)
        sched = obs_scheduler(cs, dump_path=str(path))
        cs.create_pod(MakePod().name("p").req({"cpu": "100m"}).obj())
        with pytest.raises(BudgetExceeded):
            # a 1-byte budget: no chunk shape can ever fit
            sched.drain_backlog(budget_bytes=1)
        assert path.exists()

    def test_fleet_sim_invariant_violation_dumps_every_replica(
        self, tmp_path
    ):
        from kubernetes_tpu.sim.fleet import FleetSimHarness
        from kubernetes_tpu.sim.invariants import _record

        dump = tmp_path / "fleet_flight.jsonl"
        h = FleetSimHarness(
            "fleet_mixed", seed=1, cycles=2, replicas=2,
            flight_dump=str(dump),
        )
        _record(h.violations, "capacity", 0, "synthetic for the test")
        res = h.run()
        assert set(res.flight_dumps.values()) == {"r0", "r1"}
        for path in res.flight_dumps:
            assert path.startswith(str(dump))
            assert (tmp_path / path.split("/")[-1]).exists()


class TestSpanSampling:
    def test_bind_and_enqueue_spans_sample_deterministically(self):
        """The high-volume families (per-event enqueue, per-pod bind)
        sample 1-in-N with a deterministic counter: the first
        occurrence always lands, counts match the configured rate, and
        sampled spans carry sample_n so a reader can re-scale."""
        cs = mk_cluster(2, cpu="64")
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=16,
                solver=ExactSolverConfig(tie_break="first"),
                obs=ObsConfig(
                    spans=True, journal=True,
                    enqueue_span_sample_n=4, bind_span_sample_n=4,
                ),
            ),
        )
        for i in range(8):
            cs.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "100m"}).obj()
            )
        sched.run_until_settled()
        spans = sched.flight.spans()
        binds = [s for s in spans if s["name"] == "bind"]
        # 8 commits at 1-in-4: exactly commits 1 and 5 sampled
        assert len(binds) == 2
        assert all(s["attrs"]["sample_n"] == 4 for s in binds)
        enqueues = [s for s in spans if s["name"] == "enqueue"]
        assert enqueues  # the first event always samples
        # the journal stays COMPLETE regardless of span sampling
        assert len(sched.journal.last_outcomes()) == 8

    def test_sample_n_1_keeps_every_span(self):
        cs = mk_cluster(2, cpu="64")
        sched = Scheduler(
            cs,
            SchedulerConfig(
                batch_size=16,
                solver=ExactSolverConfig(tie_break="first"),
                obs=ObsConfig(
                    spans=True, journal=True,
                    enqueue_span_sample_n=1, bind_span_sample_n=1,
                ),
            ),
        )
        for i in range(4):
            cs.create_pod(
                MakePod().name(f"p{i}").req({"cpu": "100m"}).obj()
            )
        sched.run_until_settled()
        binds = [
            s for s in sched.flight.spans() if s["name"] == "bind"
        ]
        assert len(binds) == 4
        assert all("sample_n" not in s.get("attrs", {}) for s in binds)


# -- trace-id stability across a multi-chunk backlog drain --------------


class TestDrainTraceStability:
    def test_chunks_share_the_drain_root_trace(self):
        """ISSUE satellite: every chunk's spans and journal records in
        ONE drain_backlog pass carry the drain's root trace
        (drain_trace), asserted at a multi-chunk shape."""
        cs = mk_cluster(4)
        sched = obs_scheduler(cs)
        for i in range(12):
            cs.create_pod(
                MakePod().name(f"p{i:02d}").req({"cpu": "100m"}).obj()
            )
        root = sched._trace_step
        report = sched.drain_backlog(chunk_pods=4)
        assert report.chunks >= 3, "need a multi-chunk drain"
        assert report.drained == 12
        recs = [json.loads(ln) for ln in sched.journal.lines]
        drain_recs = [r for r in recs if "drain_trace" in r]
        assert drain_recs, "drain records must carry drain_trace"
        assert {r["drain_trace"] for r in drain_recs} == {root}
        # records from distinct chunks (multi-chunk proof)
        assert len({r["drain_chunk"] for r in drain_recs}) >= 3
        # dispatch spans of every chunk carry the same root
        spans = sched.flight.spans()
        drain_spans = [
            s for s in spans
            if s["name"] == "dispatch"
            and "drain_trace" in (s.get("attrs") or {})
        ]
        assert len({s["attrs"]["drain_trace"] for s in drain_spans}) == 1
        assert drain_spans[0]["attrs"]["drain_trace"] == root
        assert len({s["attrs"]["drain_chunk"] for s in drain_spans}) >= 3
        # the root drain_backlog span exists on the same trace
        roots = [s for s in spans if s["name"] == "drain_backlog"]
        assert roots and roots[0]["trace"] == root
        # the tags are gone after the drain: later records are clean
        cs.create_pod(MakePod().name("after").req({"cpu": "100m"}).obj())
        sched.run_until_settled()
        last = json.loads(sched.journal.lines[-1])
        assert "drain_trace" not in last and "drain_chunk" not in last
        # and the whole journal still validates under the strict schema
        assert validate_lines(sched.journal.lines) == []
