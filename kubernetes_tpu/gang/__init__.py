"""Gang scheduling + heterogeneity-aware placement (the DL-training
workload layer).

Pod groups are declared on the existing API objects — no new kinds:

- ``scheduling.x-k8s.io/pod-group`` (label): the group name; the gang
  id is ``namespace/name`` (a gang never spans namespaces);
- ``scheduling.x-k8s.io/pod-group-min-member`` (annotation): the
  all-or-nothing quorum. The gang is solved only once at least this
  many members are known to the queue, and either every solved member
  binds in ONE atomic commit (``ClusterState.bind_gang``) or every
  placement is released and the gang requeues with a
  ``gang_incomplete`` journal outcome. A partial gang is never bound.
- ``scheduling.x-k8s.io/workload-class`` (pod label) +
  ``scheduling.x-k8s.io/accelerator-class`` (node label): the
  heterogeneity axis. ``fold_throughput`` folds the configured
  per-(workload, accelerator-class) effective-throughput matrix into
  the score pipeline's extra-score table (Gavel's objective: land the
  gang where throughput-per-chip is highest, not merely where it
  fits).

The tracker (``GangTracker``) is pure host-side bookkeeping: gang
membership readiness, assembly timestamps, and the
consecutive-incomplete count that eventually quarantines a gang no
placement will ever satisfy.
"""

from .tracker import (
    GANG_LABEL,
    MIN_MEMBER_ANNOTATION,
    GangConfig,
    GangTracker,
    GangUnsatisfiableError,
)
from .throughput import (
    ACCEL_CLASS_LABEL,
    WORKLOAD_CLASS_LABEL,
    fold_throughput,
    load_throughput_table,
)

__all__ = [
    "GANG_LABEL",
    "MIN_MEMBER_ANNOTATION",
    "ACCEL_CLASS_LABEL",
    "WORKLOAD_CLASS_LABEL",
    "GangConfig",
    "GangTracker",
    "GangUnsatisfiableError",
    "fold_throughput",
    "load_throughput_table",
]
