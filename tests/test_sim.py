"""Tier-1 sim scenarios: short fixed-seed runs of the deterministic
cluster simulator (kubernetes_tpu/sim) against the REAL scheduler.

Three pins:
1. determinism — two fresh runs of the same seed+profile produce
   byte-identical traces and identical final bindings;
2. the ISSUE-2 acceptance scenario — bind-failure + watch-delay
   injection against run_pipelined completes with zero invariant
   violations while the livelock backstop (PR-1's
   scheduler_pipeline_fallback_total) engages at least once;
3. replay — a recorded trace re-executes to identical final bindings.

Long multi-profile soaks live in test_sim_soak.py (@slow).
"""

import pytest

from kubernetes_tpu import metrics
from kubernetes_tpu.sim import SimHarness, replay_trace, run_sim

CYCLES = 6  # small: tier-1 budget; soak covers depth


def test_churn_heavy_deterministic():
    a = run_sim("churn_heavy", seed=0, cycles=CYCLES)
    b = run_sim("churn_heavy", seed=0, cycles=CYCLES)
    assert a.trace.lines == b.trace.lines
    assert a.trace.digest() == b.trace.digest()
    assert a.bindings == b.bindings
    assert a.violations == b.violations == []
    assert a.settled and b.settled


def test_churn_heavy_pipelined_fence_and_backstop():
    """The acceptance scenario: churn_heavy injects bind failures and
    delayed/duplicated watch delivery against run_pipelined. The run
    must finish with zero invariant violations, and the sustained
    fence-discard churn must have engaged the pipeline's livelock
    backstop at least once (proving the sim reaches the dispatch→apply
    window, not just the idle gaps between cycles)."""
    res = run_sim("churn_heavy", seed=0, cycles=CYCLES)
    assert res.summary["pipelined"] is True
    assert res.violations == []
    assert res.settled
    assert res.summary["bind_faults"] > 0  # faults actually fired
    assert res.summary["watch_delivered"] > 0
    assert res.summary["discards"] >= 1  # fence actually discarded solves
    assert res.summary["pipeline_fallbacks"] >= 1  # backstop engaged


def test_bind_storms_external_actors():
    """External competing binds + injected bind conflicts: the
    assume/forget protocol and ghost-entry handling under a racing
    actor, with every invariant holding."""
    res = run_sim("bind_storms", seed=1, cycles=CYCLES)
    assert res.violations == []
    assert res.settled
    assert res.summary["bind_faults"] > 0


def test_trace_replays_to_identical_bindings(tmp_path):
    path = tmp_path / "trace.jsonl"
    rec = run_sim("churn_heavy", seed=5, cycles=CYCLES)
    rec.trace.dump(path)
    rep = replay_trace(path)
    assert rep.replay_divergence is None
    assert rep.bindings == rec.bindings
    assert [v.as_dict() for v in rep.violations] == [
        v.as_dict() for v in rec.violations
    ]


def test_sim_metrics_registered():
    """MET001 satellite: every scheduler_sim_* series the sim records
    is registered in the dedicated registry (a typo would only blow up
    on the first faulted run otherwise)."""
    run_sim("node_flaps", seed=2, cycles=3)
    names = {
        family.name for family in metrics.REGISTRY.collect()
    }
    for expected in (
        "scheduler_sim_events",
        "scheduler_sim_faults_injected",
        "scheduler_sim_invariant_violations",
        "scheduler_sim_cycles",
    ):
        assert expected in names, expected


def test_crash_restart_acceptance():
    """The ISSUE-8 acceptance scenario: kill the scheduler mid-batch
    (after assume, before bind), restart a fresh incarnation on the
    same ClusterState, and every pod still reaches a terminal journal
    outcome with zero double-binds — byte-deterministically."""
    a = run_sim("crash_restart", seed=0, cycles=8)
    assert a.violations == []
    assert a.settled
    assert a.summary["crashes"] == 1  # the kill actually fired
    assert a.summary["incarnations"] == 2
    # the crash orphaned work and the fresh incarnation terminally
    # journaled its re-adoption
    assert a.summary["recovered_records"] >= 1
    # byte-determinism across the restart boundary too
    b = run_sim("crash_restart", seed=0, cycles=8)
    assert a.trace.lines == b.trace.lines
    assert a.journal_lines == b.journal_lines
