"""Test configuration: force JAX onto CPU with 8 virtual devices BEFORE any
jax import, so sharding tests exercise a multi-chip mesh without TPU hardware
(SURVEY.md §6.7 — single real chip; mesh logic validated on host devices)."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep compile times predictable on the 1-vCPU host.
os.environ.setdefault("JAX_ENABLE_X64", "1")
