"""ops/fastmath.floor_div_exact must be bit-identical to `//` on its
documented contract: non-negative numerators, positive denominators,
quotients below 2^23 (every kernel call site has q <= ~10^4 — scores
scaled by 100)."""

import jax.numpy as jnp
import numpy as np
from _hypothesis_compat import given, settings, st

from kubernetes_tpu.ops.fastmath import floor_div_exact


@settings(max_examples=200, deadline=None)
@given(
    q=st.integers(min_value=0, max_value=(1 << 23) - 1),
    den=st.integers(min_value=1, max_value=(1 << 36)),
    data=st.data(),
)
def test_scalar_matches_floordiv(q, den, data):
    r = data.draw(st.integers(min_value=0, max_value=den - 1))
    num = q * den + r  # true quotient is exactly q
    got = int(
        floor_div_exact(jnp.asarray(num, jnp.int64), jnp.asarray(den, jnp.int64))
    )
    assert got == q


def test_vector_matches_floordiv():
    rng = np.random.default_rng(0)
    # score-shaped ranges (the hot path): quotients <= 100, int64 operands
    alloc = rng.integers(1, 64 << 30, size=4096).astype(np.int64)
    req = (alloc * rng.random(4096)).astype(np.int64)
    got = np.asarray(
        floor_div_exact(jnp.asarray((alloc - req) * 100), jnp.asarray(alloc))
    )
    np.testing.assert_array_equal(got, (alloc - req) * 100 // alloc)
    # larger quotients near the contract edge
    den = rng.integers(1, 1 << 20, size=4096).astype(np.int64)
    q = rng.integers(0, 1 << 23, size=4096).astype(np.int64)
    num = q * den + rng.integers(0, 1 << 19, size=4096).astype(np.int64) % den
    got = np.asarray(floor_div_exact(jnp.asarray(num), jnp.asarray(den)))
    np.testing.assert_array_equal(got, num // den)


def test_int32_matches_floordiv():
    rng = np.random.default_rng(1)
    den = rng.integers(1, 1 << 8, size=4096).astype(np.int32)
    q = rng.integers(0, 1 << 22, size=4096).astype(np.int32)
    num = q * den + rng.integers(0, 1 << 7, size=4096).astype(np.int32) % den
    got = np.asarray(floor_div_exact(jnp.asarray(num), jnp.asarray(den)))
    np.testing.assert_array_equal(got, num // den)
