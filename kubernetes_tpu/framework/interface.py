"""Scheduling Framework plugin interfaces — the in-process, extension-
point-shaped API of SURVEY §8.2, mirroring
pkg/scheduler/framework/interface.go so plugin code and plugin tests read
like their upstream counterparts:

- `Status` / `StatusCode` (interface.go#Status, #Code): Success,
  Unschedulable, UnschedulableAndUnresolvable, Wait, Skip, Error;
- `CycleState` (framework/cycle_state.go): per-pod keyed scratch with
  read/write/clone;
- plugin protocols named for their extension points (PreFilterPlugin,
  FilterPlugin, ScorePlugin) with the upstream method shapes.

Two consumption paths:
1. `framework.runtime.Framework` runs the points host-side over API
   objects — the fixture upstream plugin tests build with
   runtime.NewFramework.
2. Out-of-tree plugins plug into the TPU solve itself via
   SchedulerConfig.out_of_tree_plugins: because the device pipeline is
   class-vectorized, a custom plugin's Filter/Score run host-side once
   per (pod scheduling class, node) and fold into the per-class static
   mask / score tables the fused kernel already consumes — the TPU-shaped
   equivalent of registering an in-process Go plugin. Contract for
   solver-path plugins: depend only on node state plus the pod fields in
   the scheduling-class identity — labels, annotations, and the in-tree
   spec fields (selectors, affinity, tolerations, requests, ports,
   spread) — never on other pending pods or on per-pod uniqueness like
   the name (two pods identical in those fields share one verdict by
   construction).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Mapping

from ..api.objects import Node, Pod

MAX_NODE_SCORE = 100  # interface.go#MaxNodeScore
MIN_NODE_SCORE = 0


class StatusCode(enum.IntEnum):
    SUCCESS = 0
    ERROR = 1
    UNSCHEDULABLE = 2
    UNSCHEDULABLE_AND_UNRESOLVABLE = 3
    WAIT = 4
    SKIP = 5


@dataclass(frozen=True)
class Status:
    code: StatusCode = StatusCode.SUCCESS
    reasons: tuple[str, ...] = ()

    @staticmethod
    def success() -> "Status":
        return Status()

    @staticmethod
    def unschedulable(*reasons: str) -> "Status":
        return Status(StatusCode.UNSCHEDULABLE, tuple(reasons))

    @staticmethod
    def error(*reasons: str) -> "Status":
        return Status(StatusCode.ERROR, tuple(reasons))

    @property
    def is_success(self) -> bool:
        return self.code == StatusCode.SUCCESS

    @property
    def is_rejection(self) -> bool:
        return self.code in (
            StatusCode.UNSCHEDULABLE,
            StatusCode.UNSCHEDULABLE_AND_UNRESOLVABLE,
        )


class CycleState:
    """Per-scheduling-cycle keyed scratch (cycle_state.go#CycleState)."""

    def __init__(self) -> None:
        self._data: dict[str, Any] = {}

    def read(self, key: str) -> Any:
        if key not in self._data:
            raise KeyError(key)  # cycle_state.go#ErrNotFound
        return self._data[key]

    def write(self, key: str, value: Any) -> None:
        self._data[key] = value

    def delete(self, key: str) -> None:
        self._data.pop(key, None)

    def clone(self) -> "CycleState":
        c = CycleState()
        c._data = dict(self._data)
        return c


class Plugin:
    """Base: every plugin has a Name (interface.go#Plugin)."""

    def name(self) -> str:
        return type(self).__name__


class PreFilterPlugin(Plugin):
    def pre_filter(self, state: CycleState, pod: Pod) -> Status:
        return Status.success()


class FilterPlugin(Plugin):
    def filter(
        self, state: CycleState, pod: Pod, node: Node,
        placed: tuple[Pod, ...] = (),
    ) -> Status:
        """interface.go#FilterPlugin.Filter. ``placed`` carries the node's
        resident pods (the NodeInfo view) for host-side runs; solver-path
        plugins should ignore it (class-vectorized folding evaluates
        against node state only)."""
        raise NotImplementedError

    def weight(self) -> int:  # parity with ScorePlugin for registries
        return 0


class ScorePlugin(Plugin):
    def score(self, state: CycleState, pod: Pod, node: Node) -> int:
        """interface.go#ScorePlugin.Score: 0..MAX_NODE_SCORE."""
        raise NotImplementedError

    def normalize_score(
        self, state: CycleState, pod: Pod, scores: Mapping[str, int]
    ) -> dict[str, int] | None:
        """Optional ScoreExtensions#NormalizeScore: node name -> score.
        Return None to keep raw scores."""
        return None

    def weight(self) -> int:
        return 1


@dataclass
class Registry:
    """plugins by extension point (runtime/registry.go shape)."""

    pre_filter: list[PreFilterPlugin] = field(default_factory=list)
    filter: list[FilterPlugin] = field(default_factory=list)
    score: list[ScorePlugin] = field(default_factory=list)
