"""TPU003 — dtype discipline in solver/ops tensor constructors.

``jnp.array([True])`` / ``jnp.zeros(n)`` / ``jnp.full(n, 0.5)`` without
an explicit dtype take jax's weak-type defaults: the array's dtype then
depends on x64 mode and on the literal's Python type, which silently
forks the jit cache (same shapes, different dtypes -> recompile) and
upcasts int64 node tables through float64 intermediates. Under ``ops/``
and ``solver/`` every constructor names its dtype; a float literal
without one is called out specifically (the classic weak-float leak).

Positional dtypes count (``jnp.zeros(n, jnp.int32)``), as does
``dtype=``; ``jnp.zeros_like``/``astype`` are inherently typed and out
of scope.
"""

from __future__ import annotations

import ast

from ..core import Finding, Pass

# constructor -> index of the positional dtype slot
_CONSTRUCTORS = {"array": 1, "zeros": 1, "ones": 1, "full": 2}


def _has_float_literal(expr: ast.expr) -> bool:
    return any(
        isinstance(n, ast.Constant) and isinstance(n.value, float)
        for n in ast.walk(expr)
    )


class DtypeDisciplinePass(Pass):
    rule = "TPU003"
    title = "missing explicit dtype"

    def run(self, module, ctx):
        if not any(module.rel.startswith(p) for p in ctx.dtype_paths):
            return []
        findings: list[Finding] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "jnp"
                and f.attr in _CONSTRUCTORS
            ):
                continue
            if any(kw.arg == "dtype" for kw in node.keywords):
                continue
            if len(node.args) > _CONSTRUCTORS[f.attr]:
                continue  # positional dtype
            detail = (
                "a bare float literal rides the weak-type default"
                if any(_has_float_literal(a) for a in node.args)
                else "dtype falls to the weak-type default"
            )
            findings.append(
                Finding(
                    self.rule, module.path, node.lineno,
                    f"jnp.{f.attr}(...) without explicit dtype ({detail})",
                    hint="pass dtype= (e.g. jnp.int64/jnp.bool_) so the "
                    "jit cache keys stay stable across x64 modes",
                )
            )
        return findings
