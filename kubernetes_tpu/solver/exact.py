"""Exact-parity solver: a lax.scan over pods in queue order (SURVEY.md §8.4
mode 1).

This replaces the reference's scheduleOne hot path
(pkg/scheduler/schedule_one.go#schedulePod -> findNodesThatFitPod ->
prioritizeNodes -> selectHost) with one compiled program: each scan step is a
dense filter-mask + score over ALL nodes at once (the per-(pod,node) Go
interface-call overhead becomes one fused XLA loop body), and the
assume-pod state mutation (cache.AssumePod) becomes an in-carry scatter so
the next step sees updated node state — preserving the reference's strict
pod-by-pod sequential semantics, which is what "binding parity" means.

selectHost tie-break: the reference reservoir-samples uniformly among
max-score ties with an unseeded RNG (schedule_one.go#selectHost). Bit-parity
is impossible; we offer:
- "random": uniform among ties from a seeded PRNG key (documented divergence)
- "first":  lowest node index among ties (deterministic, used by parity tests)
Either way the pick is provably inside the reference's tie set, which is the
parity definition from SURVEY.md §8.8.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..ops import noderesources as nr
from ..tensorize.schema import CPU_IDX, MEM_IDX, NodeBatch, PodBatch

TIE_RANDOM = "random"
TIE_FIRST = "first"


@dataclass(frozen=True)
class ExactSolverConfig:
    tie_break: str = TIE_RANDOM
    seed: int = 0
    # plugin weights (framework runtime multiplies normalized scores by
    # config weights; defaults are 1 for both of these plugins)
    fit_weight: int = 1
    balanced_weight: int = 1
    balanced_fdtype: str = "float32"  # float64 for bit-parity on CPU tests


def _solve_scan(
    alloc,  # [K, N] int
    max_pods,  # [N] int32
    node_static_mask,  # [N] bool — valid & schedulable
    used0,  # [K, N] int
    nonzero_used0,  # [2, N] int
    pod_count0,  # [N] int32
    req,  # [P, K] int
    req_mask,  # [P, K] bool
    nonzero_req,  # [P, 2] int
    pod_valid,  # [P] bool — valid & statically feasible
    key,  # PRNG key
    *,
    tie_break: str,
    fit_weight: int,
    balanced_weight: int,
    fdtype,
):
    alloc2 = alloc[: MEM_IDX + 1]  # cpu, memory rows for scoring
    weights2 = jnp.ones(2, dtype=alloc.dtype)

    def step(carry, xs):
        used, nonzero_used, pod_count, k = carry
        r, rmask, nz, pvalid = xs

        mask = (
            nr.fit_mask(r, rmask, alloc, used, pod_count, max_pods)
            & node_static_mask
        )
        requested = nr.scoring_requested(nz, nonzero_used)
        score = fit_weight * nr.least_allocated_score(requested, alloc2, weights2)
        score = score + balanced_weight * nr.balanced_allocation_score(
            requested, alloc2, fdtype=fdtype
        )
        score = jnp.where(mask, score, -1)

        best = jnp.max(score)
        feasible = best >= 0
        ties = (score == best) & mask
        csum = jnp.cumsum(ties)
        if tie_break == TIE_RANDOM:
            k, sub = jax.random.split(k)
            n_ties = csum[-1]
            pick_rank = jax.random.randint(sub, (), 0, jnp.maximum(n_ties, 1))
        else:
            pick_rank = 0
        pick = jnp.argmax(csum > pick_rank).astype(jnp.int32)

        found = feasible & pvalid
        d = found.astype(alloc.dtype)
        used = used.at[:, pick].add(r * d)
        nonzero_used = nonzero_used.at[:, pick].add(nz * d)
        pod_count = pod_count.at[pick].add(found.astype(jnp.int32))

        assignment = jnp.where(found, pick, -1).astype(jnp.int32)
        return (used, nonzero_used, pod_count, k), assignment

    (used, nonzero_used, pod_count, _), assignments = jax.lax.scan(
        step,
        (used0, nonzero_used0, pod_count0, key),
        (req, req_mask, nonzero_req, pod_valid),
    )
    return assignments, used, nonzero_used, pod_count


_solve_scan_jit = jax.jit(
    _solve_scan,
    static_argnames=("tie_break", "fit_weight", "balanced_weight", "fdtype"),
    donate_argnums=(3, 4, 5),
)


class ExactSolver:
    """Host-facing wrapper: NodeBatch/PodBatch in, assignments out, node
    state written back (the device-side 'assume')."""

    def __init__(self, config: ExactSolverConfig | None = None):
        self.config = config or ExactSolverConfig()
        self._step_count = 0
        # int64 resource arithmetic is non-negotiable (memory bytes overflow
        # int32); jax 0.9+axon ignores the JAX_ENABLE_X64 env var, so enable
        # it here rather than trusting the embedding application.
        if not jax.config.jax_enable_x64:
            jax.config.update("jax_enable_x64", True)

    def solve(self, nodes: NodeBatch, pods: PodBatch) -> np.ndarray:
        """Returns assignments [num_pods] of node indices (-1 = unschedulable)
        and updates ``nodes``' used/nonzero_used/pod_count in place."""
        cfg = self.config
        fdtype = jnp.float64 if cfg.balanced_fdtype == "float64" else jnp.float32
        key = jax.random.PRNGKey(cfg.seed + self._step_count)
        self._step_count += 1
        node_static_mask = nodes.valid & nodes.schedulable
        assignments, used, nonzero_used, pod_count = _solve_scan_jit(
            jnp.asarray(nodes.allocatable),
            jnp.asarray(nodes.max_pods),
            jnp.asarray(node_static_mask),
            jnp.asarray(nodes.used),
            jnp.asarray(nodes.nonzero_used),
            jnp.asarray(nodes.pod_count),
            jnp.asarray(pods.req),
            jnp.asarray(pods.req_mask),
            jnp.asarray(pods.nonzero_req),
            jnp.asarray(pods.valid & pods.feasible_static),
            key,
            tie_break=cfg.tie_break,
            fit_weight=cfg.fit_weight,
            balanced_weight=cfg.balanced_weight,
            fdtype=fdtype,
        )
        # np.array(copy=True): np.asarray on a jax array yields a READ-ONLY
        # view, which would freeze the snapshot's dirty-column writes
        nodes.used = np.array(used)
        nodes.nonzero_used = np.array(nonzero_used)
        nodes.pod_count = np.array(pod_count)
        return np.asarray(assignments)[: pods.num_pods]
