"""Cross-shard occupancy exchange: the compact rows fleet replicas
trade before committing placements, so cross-shard
``PodTopologySpread`` / inter-pod anti-affinity stay enforceable
without a global lock.

A replica's shard-filtered cache (state/cluster.py filtered watch)
deliberately contains ONLY its own nodes and pods — peers' placements
are invisible to it. The exchange is the one channel that crosses the
partition: each replica publishes

- **node rows** — (node, zone) for every node it owns: the domain
  inventory peers need to compute global spread skew (an empty peer
  zone is a min-count domain even though no pod row mentions it);
- **pod rows** — (pod, node, zone, namespace, labels) for every
  *label-bearing* pod it has assumed (``pending``) or bound
  (``committed``) on its shard. Label-free pods can never match a
  spread selector or an (anti-)affinity term, so they stay off the
  wire — that is what keeps the rows compact.

Rows are the host-side mirror of the device-resident
``BatchCarriedUsage`` occupancy carry (solver/exact.py): the same
"placements earlier in flight count against constraints solved later"
idea, stretched across replicas instead of chained sub-batches — and
they ride the same tensorcodec wire framing over the bulk gRPC
boundary (server/bulk.py ``ExchangeOccupancy``).

Concurrency contract: the hub serializes every mutation under one lock
and bumps a monotonically increasing ``version``, and admission is
atomic AT THE HUB for every fleet shape — in-process or cross-process.
``compare_and_stage`` is a fenced compare-and-swap on pending rows:
the replica re-checks its cross-shard constraints host-side against a
peer view taken at version V, then lands the pending row only if the
hub is STILL at V (any interleaved stage/commit/withdraw by a peer
moved it). Two replicas racing a hard-spread placement therefore can
never both land it: the hub serializes the two CAS calls, the first
wins, the second gets a typed ``AdmitConflict`` and re-admits against
the fresh rows (which now include the winner's pending row). The CAS
is *fenced* with the PR 8 token discipline: ``retire`` (a membership
transition declaring the replica dead) revokes its hub write
privilege, so a zombie's CAS — or any other row mutation — rejects
with ``AdmitConflict(fenced=True)`` until the replica re-registers by
wholesale republish (``publish_nodes`` / ``replace_pod_rows``, the
resync path every heal already takes). Cross-process replicas reach
all of this over the bulk service's ``HubOp`` RPC via
``fleet.runtime.RemoteOccupancyExchange``; version conflicts map to
gRPC ABORTED and fenced conflicts to FAILED_PRECONDITION — semantic
rejections the BulkClient never retries (unlike UNAVAILABLE).

Granularity scope note: the CAS compares against the ONE hub-wide
version, so any interleaved write — even a row that cannot touch the
admitted pod's spread domain — costs the admit a re-fetch/re-check
round (bounded by FleetRuntime._CAS_ATTEMPTS, then an ordinary
requeue; ``scheduler_fleet_admit_cas_conflict_total`` is the
observability). Safe by construction, and the write-behind batching in
RemoteOccupancyExchange collapses most benign churn into one bump per
flush; per-domain versioning is the refinement if constrained-cohort
contention ever shows up in that counter (ROADMAP fleet depth note).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, replace
from typing import Iterable, Mapping

import numpy as np

from .. import metrics

PENDING = "pending"
COMMITTED = "committed"


class ExchangeUnreachable(Exception):
    """The occupancy hub cannot be reached from this replica (network
    partition / hub outage). Raised by every hub operation while the
    replica is partitioned; FleetRuntime degrades to its cached peer
    view, whose growing age drives admission conservative
    (fleet/runtime.py occupancy-staleness bounds)."""


class AdmitConflict(Exception):
    """Typed hub-side rejection of a row mutation — the cross-process
    analog of the state service's fenced ``ApiError`` (a flag, not a
    message-prefix contract).

    ``fenced=False``: a ``compare_and_stage`` lost its compare — the
    hub version moved past ``expected_version`` between the caller's
    peer-view fetch and its CAS (a peer landed a row first). The caller
    re-fetches and re-admits; ``version`` carries the hub version at
    rejection time. ``fenced=True``: the caller's hub write privilege
    was revoked by ``retire`` (its membership was declared dead) — no
    mutation lands until it re-registers wholesale via resync.

    This is a SEMANTIC rejection, never a transport failure: over the
    wire it maps to gRPC ABORTED / FAILED_PRECONDITION, which the
    BulkClient deliberately does not retry (a blind retry of a lost
    race would re-land the very write the CAS exists to reject —
    the committing-Solve never-retries rule)."""

    def __init__(
        self, message: str, *, fenced: bool = False,
        version: int | None = None,
    ) -> None:
        self.fenced = fenced
        self.version = version
        super().__init__(message)


@dataclass(frozen=True)
class NodeRow:
    """Domain-inventory row: one owned node and its zone key."""

    node: str
    zone: str = ""


@dataclass(frozen=True)
class PodRow:
    """One label-bearing placement a replica holds (assumed or
    bound)."""

    pod: str  # ns/name key
    node: str
    zone: str
    namespace: str
    labels: tuple[tuple[str, str], ...]  # sorted items
    state: str = PENDING  # pending | committed

    @staticmethod
    def for_pod(pod, node: str, zone: str, state: str = PENDING) -> "PodRow":
        return PodRow(
            pod=pod.key,
            node=node,
            zone=zone,
            namespace=pod.namespace,
            labels=tuple(sorted(pod.labels.items())),
            state=state,
        )


@dataclass(frozen=True)
class PeerView:
    """One consistent snapshot of every OTHER replica's rows, plus the
    hub version it was taken at — the Conflict-on-stale fence value.
    ``peer_ages`` carries, per peer that has ever published, the
    seconds since its last successful publish at view time: a peer
    partitioned from the hub stops publishing, its age grows, and
    admission against its frozen rows turns conservative once the age
    passes the staleness bound (fleet/runtime.py)."""

    version: int
    node_rows: tuple[NodeRow, ...]
    pod_rows: tuple[PodRow, ...]
    peer_ages: tuple[tuple[str, float], ...] = ()


class OccupancyExchange:
    """The in-process hub (one per fleet; the sim's replicas share it
    directly, cross-process deployments reach it through the bulk
    service's ``ExchangeOccupancy`` RPC). All iteration is sorted so
    any serialized view is deterministic."""

    def __init__(self, clock=None) -> None:
        from ..utils.clock import Clock

        self._lock = threading.Lock()
        self._version = 0
        # publish timestamps (staleness bounds): replica -> when it
        # last successfully wrote anything to the hub. Off the
        # injectable clock so the sim's virtual timeline covers row
        # aging too.
        self._clock = clock or Clock()
        self._published_at: dict[str, float] = {}
        # replicas currently partitioned from the hub (sim fault seam):
        # every operation FROM a partitioned replica raises
        # ExchangeUnreachable — its writes don't land, its reads fail,
        # and its published_at freezes, which is what peers' staleness
        # bounds key off.
        self._partitioned: set[str] = set()
        # replicas whose hub write privilege is revoked (retire()): the
        # PR 8 fencing-token discipline extended to the hub — a peer
        # observed this replica's lease stale and retired it, so its
        # row mutations must not land until it re-registers by
        # wholesale republish (publish_nodes / replace_pod_rows — the
        # path every heal's forced resync already takes). Reads stay
        # open: a zombie reading rows is harmless, a zombie WRITING
        # rows would distort every peer's admission.
        self._revoked: set[str] = set()
        # metric children resolved once: stage/commit run per placed
        # pod on the scheduler's apply path, and the label lookup is
        # measurable there (ops mirror the metric help string)
        self._m = {
            op: metrics.fleet_occupancy_rows_total.labels(op)
            for op in ("staged", "committed", "withdrawn", "retired",
                       "handoff")
        }
        self._node_rows: dict[str, dict[str, NodeRow]] = {}  # replica -> node -> row
        self._pod_rows: dict[str, dict[str, PodRow]] = {}  # replica -> pod -> row
        # pod handoffs: to-replica -> pod key -> (hop count, journey
        # trace id). A replica whose shard cannot legally host a routed
        # pod (persistent cross-shard conflict) releases it here for
        # the next replica in the pod's rendezvous chain
        # (fleet/runtime.py). The trace id is the PR 3 journey trace
        # threaded ACROSS the handoff: the adopting replica's journal
        # records continue the same trace, so `obs explain --fleet`
        # renders enqueue→handoff→re-admit→bind as ONE trace.
        self._handoffs: dict[str, dict[str, tuple[int, str]]] = {}
        # append-only journal aggregation surface (the cross-replica
        # obs tentpole): replicas ship bounded decision-journal
        # segments — piggybacked on the existing write-behind flush,
        # no new RPC cadence — and `obs explain --fleet` reads the
        # merged stream. Bounded: a long-lived hub keeps the recent
        # window, not unbounded history (replicas' own sinks are the
        # durable store).
        from collections import deque

        self._journal: deque[str] = deque(maxlen=262_144)
        # replicas whose solve breaker is open (degraded-mode solve
        # resilience): peers prefer them LAST in rendezvous handoff
        # chains — don't route refugees to a sick replica. The replica
        # keeps serving its own shard (the fallback ladder guarantees
        # forward progress); this flag only shapes cross-shard routing.
        self._degraded: set[str] = set()

    @property
    def version(self) -> int:
        with self._lock:
            return self._version

    # -- partition seam (hub reachability, per replica) --

    def set_partitioned(self, replica: str, partitioned: bool) -> None:
        """Sim/fault seam: model ``replica`` losing (or regaining) its
        network path to the hub. While partitioned, every hub operation
        from that replica raises ExchangeUnreachable."""
        with self._lock:
            if partitioned:
                self._partitioned.add(replica)
            else:
                self._partitioned.discard(replica)

    def _check_reachable(self, replica: str) -> None:
        # callers hold self._lock or tolerate the benign race (the
        # partition flag only ever flips between whole sim cycles)
        if replica in self._partitioned:
            raise ExchangeUnreachable(
                f"replica {replica} is partitioned from the occupancy hub"
            )

    def _check_write_fence(self, replica: str) -> None:
        # callers hold self._lock
        if replica in self._revoked:
            raise AdmitConflict(
                f"replica {replica} was retired at the hub (membership "
                "declared it dead): row mutations are fenced until it "
                "re-registers by wholesale republish",
                fenced=True,
                version=self._version,
            )

    def _touch(self, replica: str) -> None:
        """Refresh ``replica``'s liveness stamp. Rows are maintained
        incrementally (every change stages/commits/withdraws
        immediately), so between changes no-news-is-good-news AS LONG
        AS the replica can still reach the hub: any successful
        reachability-gated operation — reads included — proves its
        rows are current and refreshes the stamp. Without the
        read-side touch, a healthy but IDLE peer (no pod churn) would
        age past max_row_age_s and starve every cross-shard-
        constrained pod fleet-wide (review-caught)."""
        self._published_at[replica] = self._clock.now()

    def peers_version(self, replica: str) -> int:
        """The hub version as seen from ``replica`` (reachability-
        gated, unlike the raw ``version`` property)."""
        with self._lock:
            self._check_reachable(replica)
            self._touch(replica)
            return self._version

    # -- publishing --

    def publish_nodes(self, replica: str, rows: Iterable[NodeRow]) -> None:
        """Replace ``replica``'s domain inventory (called at startup
        and on every resync — the owned set is replaced wholesale, not
        diffed, so a missed event can never leave a stale row). A
        wholesale republish is the replica re-asserting itself from
        cluster truth, so it also clears a hub write fence (the healed
        zombie's forced resync routes here)."""
        with self._lock:
            self._check_reachable(replica)
            self._revoked.discard(replica)
            self._version += 1
            self._node_rows[replica] = {r.node: r for r in rows}
            self._touch(replica)

    def stage(self, replica: str, row: PodRow) -> None:
        with self._lock:
            self._check_reachable(replica)
            self._check_write_fence(replica)
            self._version += 1
            self._pod_rows.setdefault(replica, {})[row.pod] = row
            self._touch(replica)
        self._m["staged"].inc()

    def compare_and_stage(
        self, replica: str, row: PodRow, expected_version: int
    ) -> int:
        """Cross-process atomic admit: land ``row`` as pending ONLY if
        the hub is still at ``expected_version`` — the version the
        caller's host-side constraint recheck ran against. Any
        interleaved mutation (a peer's stage/commit/withdraw, a
        handoff, a membership retire) moved the version, so the
        caller's view may hide a racing placement: reject with a typed
        ``AdmitConflict`` and let the caller re-fetch + re-admit.
        Returns the new hub version on success. Fenced (retired)
        replicas reject regardless of version."""
        with self._lock:
            self._check_reachable(replica)
            self._check_write_fence(replica)
            if self._version != expected_version:
                raise AdmitConflict(
                    f"hub version moved to {self._version} past the "
                    f"admitted view at {expected_version}: a peer's row "
                    "landed first — re-fetch and re-admit",
                    version=self._version,
                )
            self._version += 1
            self._pod_rows.setdefault(replica, {})[row.pod] = row
            self._touch(replica)
            version = self._version
        self._m["staged"].inc()
        return version

    def replace_pod_rows(self, replica: str, rows: Iterable[PodRow]) -> None:
        """Replace ``replica``'s pod rows wholesale (resync): rows are
        rebuilt from cluster truth whenever the partition moves, so a
        pod whose DELETE the shard filter later hides from this
        replica can never leave a ghost row behind. Clears a hub write
        fence like publish_nodes (same re-registration argument)."""
        with self._lock:
            self._check_reachable(replica)
            self._revoked.discard(replica)
            self._version += 1
            self._pod_rows[replica] = {r.pod: r for r in rows}
            self._touch(replica)

    def commit(self, replica: str, pod_key: str) -> None:
        with self._lock:
            self._check_reachable(replica)
            self._check_write_fence(replica)
            row = self._pod_rows.get(replica, {}).get(pod_key)
            if row is None or row.state == COMMITTED:
                return
            self._version += 1
            self._pod_rows[replica][pod_key] = replace(row, state=COMMITTED)
            self._touch(replica)
        self._m["committed"].inc()

    def withdraw(self, replica: str, pod_key: str) -> None:
        with self._lock:
            self._check_reachable(replica)
            # fenced like every other mutation: today a retired
            # replica's rows are already dropped (nil data effect),
            # but an asymmetric escape hatch is one refactor away from
            # a zombie deleting a live row (review-caught)
            self._check_write_fence(replica)
            if self._pod_rows.get(replica, {}).pop(pod_key, None) is None:
                return
            self._version += 1
            self._touch(replica)
        self._m["withdrawn"].inc()

    def retire(self, replica: str) -> None:
        """Drop a dead replica's rows: its committed placements become
        visible to the adopting replica through its own resync re-list,
        so keeping them here would double-count. Unclaimed handoffs
        addressed to it revert to plain hash routing — the new route
        owner adopts the pod at its membership-change resync. Also
        REVOKES the replica's hub write privilege (the fencing-token
        discipline): if it is actually a zombie, its next row mutation
        (stage / CAS / commit / withdraw / handoff / degraded-flag)
        rejects with a typed fenced AdmitConflict until its healed
        incarnation re-registers wholesale."""
        with self._lock:
            self._revoked.add(replica)
            had = (
                bool(self._node_rows.pop(replica, None))
                | bool(self._pod_rows.pop(replica, None))
                | bool(self._handoffs.pop(replica, None))
            )
            self._degraded.discard(replica)
            # a retired replica's frozen publish stamp must not keep
            # peers' staleness bounds conservative forever
            self._published_at.pop(replica, None)
            if had:
                self._version += 1
        self._m["retired"].inc()

    # -- degraded flags (solve-resilience breaker state) --

    def set_degraded(self, replica: str, degraded: bool) -> None:
        """Publish/clear a replica's degraded flag (its solve circuit
        breaker tripped / re-closed). Bumps the version so peers'
        conflict-parked pods re-evaluate their handoff chains."""
        with self._lock:
            self._check_reachable(replica)
            self._check_write_fence(replica)
            if degraded == (replica in self._degraded):
                return
            if degraded:
                self._degraded.add(replica)
            else:
                self._degraded.discard(replica)
            self._version += 1
            self._touch(replica)

    def degraded_replicas(self) -> frozenset:
        with self._lock:
            return frozenset(self._degraded)

    # -- journal aggregation (obs explain --fleet's hub surface) --

    def ship_journal(self, replica: str, lines) -> None:
        """Append a replica's journal segment to the aggregation
        surface. Reachability-gated (a partitioned replica's segment
        waits out the partition with its buffered rows) but NOT
        write-fenced: journal lines are append-only observability of
        decisions that already happened — a fenced zombie's history is
        exactly what a post-mortem needs to see."""
        lines = list(lines)
        if not lines:
            return
        with self._lock:
            self._check_reachable(replica)
            self._touch(replica)
            self._journal.extend(lines)
        metrics.fleet_journal_segments_total.inc()
        metrics.fleet_journal_lines_total.inc(len(lines))

    def journal_lines(self) -> list[str]:
        """The aggregated journal stream, in arrival order. `obs
        explain --fleet` re-orders per pod with the PR 8 merge rules,
        so arrival order only needs to be deterministic, not sorted."""
        with self._lock:
            return list(self._journal)

    # -- pod handoffs --

    def hand_off(
        self, to_replica: str, pod_key: str, hops: int,
        from_replica: str | None = None,
        trace: str = "",
    ) -> None:
        with self._lock:
            if from_replica is not None:
                self._check_reachable(from_replica)
                self._check_write_fence(from_replica)
                self._touch(from_replica)
            self._version += 1
            self._handoffs.setdefault(to_replica, {})[pod_key] = (
                hops, trace,
            )
        self._m["handoff"].inc()

    def claim_handoffs(self, replica: str) -> list[tuple[str, int, str]]:
        """Pop every handoff addressed to ``replica`` (sorted, so
        claim order is deterministic). Each claim is (pod key, hops,
        journey trace id) — the trace rode the handoff row so the
        adopting replica's journal continues the SAME trace."""
        with self._lock:
            self._check_reachable(replica)
            self._touch(replica)  # liveness: the poll proves contact
            rows = self._handoffs.pop(replica, None)
            if not rows:
                return []
            self._version += 1
            return [
                (k, hops, trace)
                for k, (hops, trace) in sorted(rows.items())
            ]

    def pending_handoff_keys(self) -> set[str]:
        """Pods released by one replica and not yet claimed by the
        next — the fleet lost-pod invariant counts these as tracked."""
        with self._lock:
            return {
                k for rows in self._handoffs.values() for k in rows
            }

    # -- reading --

    def peers_view(self, replica: str) -> PeerView:
        with self._lock:
            self._check_reachable(replica)
            self._touch(replica)  # liveness: the fetch proves contact
            node_rows = tuple(
                self._node_rows[r][n]
                for r in sorted(self._node_rows)
                if r != replica
                for n in sorted(self._node_rows[r])
            )
            pod_rows = tuple(
                self._pod_rows[r][p]
                for r in sorted(self._pod_rows)
                if r != replica
                for p in sorted(self._pod_rows[r])
            )
            now = self._clock.now()
            peer_ages = tuple(
                (r, max(now - self._published_at[r], 0.0))
                for r in sorted(self._published_at)
                if r != replica
            )
            return PeerView(self._version, node_rows, pod_rows, peer_ages)

    def replica_rows(self, replica: str) -> tuple[tuple[NodeRow, ...], tuple[PodRow, ...]]:
        with self._lock:
            return (
                tuple(
                    self._node_rows.get(replica, {})[n]
                    for n in sorted(self._node_rows.get(replica, {}))
                ),
                tuple(
                    self._pod_rows.get(replica, {})[p]
                    for p in sorted(self._pod_rows.get(replica, {}))
                ),
            )


# -- wire framing (server/tensorcodec.py, the BatchCarriedUsage wire) --


def pod_row_to_list(r: PodRow) -> list:
    """JSON-meta shape of one pod row for the HubOp RPC (state rides
    inline — single-row ops don't need the columnar committed array
    the bulk ExchangeOccupancy payload uses)."""
    return [
        r.pod, r.node, r.zone, r.namespace,
        [list(kv) for kv in r.labels], r.state,
    ]


def pod_row_from_list(v) -> PodRow:
    pod, node, zone, ns, labels, state = v
    return PodRow(
        pod=pod, node=node, zone=zone, namespace=ns,
        labels=tuple((k, val) for k, val in labels), state=state,
    )


def encode_rows(
    replica: str,
    version: int,
    node_rows: Iterable[NodeRow],
    pod_rows: Iterable[PodRow],
) -> bytes:
    """One occupancy payload: row identities/labels in the JSON meta,
    the numeric columns (pending/committed flags) as wire arrays —
    the same meta + column framing the bulk solve path uses."""
    from ..server import tensorcodec

    node_rows = list(node_rows)
    pod_rows = list(pod_rows)
    meta = {
        "replica": replica,
        "version": int(version),
        "nodes": [[r.node, r.zone] for r in node_rows],
        "pods": [
            [r.pod, r.node, r.zone, r.namespace, [list(kv) for kv in r.labels]]
            for r in pod_rows
        ],
    }
    committed = np.fromiter(
        (1 if r.state == COMMITTED else 0 for r in pod_rows),
        dtype=np.int8,
        count=len(pod_rows),
    )
    return tensorcodec.encode(meta, {"committed": committed})


def decode_rows(
    data: bytes,
) -> tuple[str, int, list[NodeRow], list[PodRow]]:
    from ..server import tensorcodec

    meta, arrays = tensorcodec.decode(data)
    node_rows = [NodeRow(node=n, zone=z) for n, z in meta.get("nodes") or []]
    committed = arrays.get("committed")
    pod_rows = []
    for i, (pod, node, zone, ns, labels) in enumerate(meta.get("pods") or []):
        pod_rows.append(
            PodRow(
                pod=pod,
                node=node,
                zone=zone,
                namespace=ns,
                labels=tuple((k, v) for k, v in labels),
                state=(
                    COMMITTED
                    if committed is not None and i < len(committed) and committed[i]
                    else PENDING
                ),
            )
        )
    return (
        str(meta.get("replica") or ""),
        int(meta.get("version") or 0),
        node_rows,
        pod_rows,
    )


def ingest_payload(exchange: OccupancyExchange, data: bytes) -> bytes:
    """Server half of the ``ExchangeOccupancy`` RPC: replace the
    sender's rows wholesale, reply with the hub's merged view of every
    OTHER replica (encoded the same way)."""
    replica, _version, node_rows, pod_rows = decode_rows(data)
    exchange.publish_nodes(replica, node_rows)
    with exchange._lock:
        exchange._version += 1
        exchange._pod_rows[replica] = {r.pod: r for r in pod_rows}
        exchange._touch(replica)
    exchange._m["staged"].inc()
    view = exchange.peers_view(replica)
    return encode_rows("", view.version, view.node_rows, view.pod_rows)
