"""Full default-profile oracle scheduler: the reference's scheduleOne loop
with the complete default plugin pipeline, in plain Python.

This extends oracle/scheduler.py (Fit+Balanced only) with the remaining
static plugins. Mirrors:
- schedule_one.go#schedulePod: Filter all nodes -> Score feasible ->
  NormalizeScore per plugin -> x weight -> sum -> selectHost (uniform among
  max ties; the oracle reports the tie SET, per SURVEY.md §8.8 parity rules)
- default plugin weights from apis/config/v1/default_plugins.go:
  TaintToleration 3, NodeAffinity 2, PodTopologySpread 2, InterPodAffinity 2,
  NodeResourcesFit 1, NodeResourcesBalancedAllocation 1, ImageLocality 1.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

from ...api.objects import Node, Pod
from . import interpod as oip
from . import plugins as opl
from . import spread as osp
from .noderesources import (
    NodeState,
    balanced_allocation_score,
    fit_filter,
    least_allocated_score,
    most_allocated_score,
    requested_to_capacity_ratio_score,
)


@dataclass(frozen=True)
class ProfileWeights:
    """Score-plugin weights (default profile)."""

    fit: int = 1
    balanced: int = 1
    taint: int = 3
    node_affinity: int = 2
    image: int = 1
    spread: int = 2
    interpod: int = 2
    # InterPodAffinityArgs.hardPodAffinityWeight (default 1)
    hard_pod_affinity: int = 1
    # NodeResourcesFitArgs.scoringStrategy.type
    scoring_strategy: str = "LeastAllocated"
    # scoringStrategy.resources: ((name, weight), ...); default cpu/mem 1/1
    fit_resources: tuple = (("cpu", 1), ("memory", 1))
    # RequestedToCapacityRatio shape: ((utilization, score), ...)
    rtc_shape: tuple = ()


@dataclass
class OracleNode:
    """NodeInfo mirror for the full pipeline: resources + node object +
    placed pods (for ports; later affinity/spread)."""

    node: Node
    res: NodeState
    pods: list[Pod] = field(default_factory=list)
    used_ports: list[tuple[str, str, int]] = field(default_factory=list)

    def add_pod(self, pod: Pod) -> None:
        self.res.add_pod(pod)
        self.pods.append(pod)
        self.used_ports.extend(pod.host_ports())


def make_oracle_nodes(
    nodes: Sequence[Node], pods_by_node: dict[str, list[Pod]] | None = None
) -> list[OracleNode]:
    out = []
    for n in nodes:
        on = OracleNode(
            node=n,
            res=NodeState(
                name=n.name,
                allocatable=dict(n.allocatable),
                max_pods=n.allowed_pod_number,
                schedulable=not n.unschedulable,
            ),
        )
        for p in (pods_by_node or {}).get(n.name, []):
            on.add_pod(p)
        out.append(on)
    return out


class FullOracle:
    """Sequential ground-truth scheduler over the full static plugin set.
    ``volume_ctx`` (ops.oracle.volumes.VolumeContext) enables the volume
    plugin family's filters."""

    def __init__(
        self,
        nodes: list[OracleNode],
        weights: ProfileWeights | None = None,
        volume_ctx=None,
        services=(),
        spread_defaulting: str = "System",
        disabled: frozenset = frozenset(),
    ):
        self.nodes = nodes
        self.weights = weights or ProfileWeights()
        self.volume_ctx = volume_ctx
        self.services = list(services)
        self.spread_defaulting = spread_defaulting
        # plugins.filter.disabled for the profile — honored so config-driven
        # callers (preemption refinement) agree with the solver pipeline
        self.disabled = frozenset(disabled)
        self._refresh_image_states()

    def _spread_defaults(self, pod: Pod):
        if self.spread_defaulting != "System" or not self.services:
            return ()
        return osp.system_default_constraints(pod, self.services)

    def _refresh_image_states(self) -> None:
        node_objs = [on.node for on in self.nodes]
        self.image_states = opl.build_image_states(node_objs)
        self.total_nodes = len(node_objs)

    def _all_nodes_with_pods(self) -> list[tuple[Node, list[Pod]]]:
        return [(on.node, on.pods) for on in self.nodes]

    _UNSET = object()

    def filter_one(
        self,
        pod: Pod,
        on: OracleNode,
        spread_state=_UNSET,
        interpod_state=_UNSET,
    ) -> bool:
        """All Filter plugins (delegates to filter_reason so the plugin
        sequence exists exactly once). ``spread_state``/``interpod_state``
        are the per-pod PreFilter precomputations (spread: None = pod has
        no hard constraints); omitting them rebuilds per call — fine for
        single-node probes, hot paths prebuild via feasible_and_ties."""
        return (
            self.filter_reason(pod, on, spread_state, interpod_state)
            is None
        )

    def filter_reason(
        self,
        pod: Pod,
        on: OracleNode,
        spread_state=_UNSET,
        interpod_state=_UNSET,
    ) -> tuple[str, ...] | None:
        """First failing Filter plugin's reference-shaped diagnosis for
        this node (None = feasible) — the per-node Status reasons
        RunFilterPlugins would record. Usually one string; NodeResourcesFit
        reports every insufficient resource (its Status carries all of
        them upstream, and FitError counts each)."""
        if spread_state is FullOracle._UNSET:
            spread_state = osp.build_filter_state(pod, self._all_nodes_with_pods())
        if interpod_state is FullOracle._UNSET:
            interpod_state = oip.build_interpod_state(
                pod, self._all_nodes_with_pods()
            )
        from . import volumes as ovol
        from ...tensorize.plugins import VOLUME_PLUGINS

        dis = self.disabled
        if "NodeName" not in dis and not opl.node_name_filter(pod, on.node):
            return ("node(s) didn't match the requested node name",)
        if "NodeUnschedulable" not in dis and not opl.node_unschedulable_filter(
            pod, on.node
        ):
            return ("node(s) were unschedulable",)
        if "TaintToleration" not in dis and not opl.taint_toleration_filter(
            pod, on.node
        ):
            return ("node(s) had untolerated taint(s)",)
        if "NodeAffinity" not in dis and not opl.node_affinity_filter(
            pod, on.node
        ):
            return ("node(s) didn't match Pod's node affinity/selector",)
        if "NodePorts" not in dis and not opl.node_ports_filter(
            pod, on.used_ports
        ):
            return ("node(s) didn't have free ports for the requested pod ports",)
        if "NodeResourcesFit" not in dis:
            failures = fit_filter(pod, on.res)
            if failures:
                return tuple(
                    "Too many pods" if r == "pods" else f"Insufficient {r}"
                    for r in failures
                )
        if (
            "PodTopologySpread" not in dis
            and spread_state is not None
            and not spread_state.check(on.node)
        ):
            return ("node(s) didn't match pod topology spread constraints",)
        if "InterPodAffinity" not in dis and not interpod_state.check(on.node):
            return ("node(s) didn't match pod affinity/anti-affinity rules",)
        if (
            self.volume_ctx is not None
            and pod.pvc_names
            and not (VOLUME_PLUGINS & dis)
            and not ovol.volume_filter(pod, on.node, self.volume_ctx)
        ):
            return ("node(s) had volume node affinity/limit conflict",)
        return None

    def fit_error(self, pod: Pod, extra=None) -> str:
        """The aggregated unschedulable message the reference's FitError
        renders (schedule_one.go#FitError.Error [U]): '0/N nodes are
        available: {count} {reason}, ...' with reasons sorted.

        ``extra(on) -> str | None`` contributes reasons from filters the
        scalar replay doesn't model (DRA claim feasibility, folded
        out-of-tree plugins); it is consulted for nodes every scalar
        filter accepts."""
        from collections import Counter

        spread_state = osp.build_filter_state(
            pod, self._all_nodes_with_pods()
        )
        interpod_state = oip.build_interpod_state(
            pod, self._all_nodes_with_pods()
        )
        reasons: Counter = Counter()
        for on in self.nodes:
            why = self.filter_reason(pod, on, spread_state, interpod_state)
            if why is None and extra is not None:
                e = extra(on)
                why = (e,) if e is not None else None
            if why is not None:
                for w in why:
                    reasons[w] += 1
        if not reasons:
            return f"0/{len(self.nodes)} nodes are available"
        detail = ", ".join(
            f"{cnt} {why}" for why, cnt in sorted(reasons.items())
        )
        return f"0/{len(self.nodes)} nodes are available: {detail}."

    def score_totals(self, pod: Pod, feasible: list[int]) -> dict[int, int]:
        """Weighted, per-plugin-normalized totals over the feasible set
        (RunScorePlugins + NormalizeScore + weights)."""
        w = self.weights
        taint_raw = [
            opl.taint_toleration_score(pod, self.nodes[i].node) for i in feasible
        ]
        na_raw = [
            opl.node_affinity_score(pod, self.nodes[i].node) for i in feasible
        ]
        taint_norm = opl.default_normalize_score(taint_raw, reverse=True)
        na_norm = opl.default_normalize_score(na_raw, reverse=False)
        spread_norm = osp.spread_scores(
            pod,
            [(self.nodes[i].node, self.nodes[i].pods) for i in feasible],
            self._all_nodes_with_pods(),
            defaults=self._spread_defaults(pod),
        )
        interpod_norm = oip.interpod_scores(
            pod,
            [self.nodes[i].node for i in feasible],
            self._all_nodes_with_pods(),
            w.hard_pod_affinity,
        )

        resources = [
            {"name": n, "weight": wt} for n, wt in w.fit_resources
        ]
        if w.scoring_strategy == "RequestedToCapacityRatio" and w.rtc_shape:
            shape = [tuple(p) for p in w.rtc_shape]

            def fit_scorer(pod, res):
                return requested_to_capacity_ratio_score(
                    pod, res, shape, resources
                )

        elif w.scoring_strategy == "MostAllocated":
            def fit_scorer(pod, res):
                return most_allocated_score(pod, res, resources)

        else:
            def fit_scorer(pod, res):
                return least_allocated_score(pod, res, resources)

        totals: dict[int, int] = {}
        for j, i in enumerate(feasible):
            on = self.nodes[i]
            t = w.fit * fit_scorer(pod, on.res)
            t += w.balanced * balanced_allocation_score(pod, on.res)
            t += w.taint * taint_norm[j]
            t += w.node_affinity * na_norm[j]
            t += w.image * opl.image_locality_score(
                pod, on.node, self.image_states, self.total_nodes
            )
            t += w.spread * spread_norm[j]
            t += w.interpod * interpod_norm[j]
            totals[i] = t
        return totals

    def feasible_set(self, pod: Pod) -> list[int]:
        all_nodes = self._all_nodes_with_pods()
        spread_state = osp.build_filter_state(pod, all_nodes)
        interpod_state = oip.build_interpod_state(pod, all_nodes)
        return [
            i
            for i, on in enumerate(self.nodes)
            if self.filter_one(pod, on, spread_state, interpod_state)
        ]

    def feasible_and_ties(self, pod: Pod) -> tuple[list[int], list[int]]:
        feasible = self.feasible_set(pod)
        if not feasible:
            return [], []
        totals = self.score_totals(pod, feasible)
        best = max(totals.values())
        ties = [i for i in feasible if totals[i] == best]
        return feasible, ties

    def schedule(self, pods: Sequence[Pod]) -> tuple[list[int], list[list[int]]]:
        """tie_break='first' deterministic run; returns (assignments, tie_sets)."""
        assignments: list[int] = []
        tie_sets: list[list[int]] = []
        for pod in pods:
            _, ties = self.feasible_and_ties(pod)
            if not ties:
                assignments.append(-1)
                tie_sets.append([])
                continue
            pick = ties[0]
            self.nodes[pick].add_pod(pod)
            assignments.append(pick)
            tie_sets.append(ties)
        return assignments, tie_sets

    def validate_assignments(
        self, pods: Sequence[Pod], assignments: Sequence[int],
        names: Sequence[str] | None = None,
        sample: "set[int] | None" = None,
    ) -> list[str]:
        """Replay solver choices, checking each against the oracle tie set.
        ``names``: solver's node name per assignment (to map index spaces);
        defaults to self.nodes order. ``sample``: step indices to verify
        (every step is still REPLAYED so state stays exact; only the
        expensive tie-set computation is skipped elsewhere) — the
        large-scale parity gate's knob (SURVEY §8.6: sampled asserts)."""
        index_of = {on.node.name: i for i, on in enumerate(self.nodes)}
        errors: list[str] = []
        for step, (pod, pick) in enumerate(zip(pods, assignments)):
            if sample is not None and step not in sample:
                if pick >= 0:
                    oi = index_of[names[step]] if names is not None else pick
                    self.nodes[oi].add_pod(pod)
                continue
            _, ties = self.feasible_and_ties(pod)
            if pick == -1:
                if ties:
                    errors.append(
                        f"step {step} pod {pod.key}: solver unschedulable but "
                        f"oracle ties {ties[:10]}"
                    )
                continue
            oi = index_of[names[step]] if names is not None else pick
            if oi not in ties:
                errors.append(
                    f"step {step} pod {pod.key}: pick {oi} not in tie set "
                    f"{ties[:10]}{'...' if len(ties) > 10 else ''}"
                )
            self.nodes[oi].add_pod(pod)
        return errors

    def validate_feasible(
        self, pods: Sequence[Pod], assignments: Sequence[int],
        names: Sequence[str] | None = None,
    ) -> list[str]:
        """Feasibility-only replay for GLOBAL planners (the convex-
        relaxation mega-planner, ISSUE 19): every placed pick must be
        in the oracle's FEASIBLE set at that step given identical
        history — no resource/pod-count overcommit, every filter
        honored — but not necessarily in the argmax tie set. A global
        plan trades per-step greedy optimality for global packing;
        tie-set parity (``validate_assignments``) is the sequential
        solvers' contract, not the planner's. Unplaced pods are not
        flagged — under-placement is an objective-quality question the
        bench/sim ratio floors own, not a validity violation."""
        index_of = {on.node.name: i for i, on in enumerate(self.nodes)}
        errors: list[str] = []
        for step, (pod, pick) in enumerate(zip(pods, assignments)):
            if pick < 0:
                continue
            feasible = self.feasible_set(pod)
            oi = index_of[names[step]] if names is not None else pick
            if oi not in feasible:
                errors.append(
                    f"step {step} pod {pod.key}: pick {oi} not in "
                    f"feasible set {feasible[:10]}"
                    f"{'...' if len(feasible) > 10 else ''}"
                )
            # follow the plan anyway to localize subsequent divergence
            self.nodes[oi].add_pod(pod)
        return errors
