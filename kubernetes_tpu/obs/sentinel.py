"""Anomaly sentinel: multi-window regression rules over the health ring.

Watches the windowed samples the telemetry layer appends to the
:class:`~kubernetes_tpu.obs.timeseries.TimeSeriesRing` — sustained
pods/s, p99 via the SLO engine, stream chain fraction, slot/fence
discard rate, CAS-conflict rate, gang incomplete-round rate, breaker
state — and fires a typed :class:`Anomaly` when a signal regresses:

- **spike** — the fast window (``fast_windows`` samples) regresses
  against the slow baseline (the ``slow_windows`` samples before it)
  by ``spike_ratio`` for ``hysteresis`` consecutive windows;
- **drift** — the trailing slow window regresses against the slow
  window before it by ``drift_ratio`` (slow degradations a fast/slow
  ratio never catches because the baseline drifts along);
- **edge** — a discrete health event inside the window (a breaker
  trip) fires immediately: the breaker already applied hysteresis.

Hysteresis, per-signal cooldowns, a min-window warmup, and absolute
floors on the near-zero-baseline rates keep the sentinel quiet on
noise; evaluation is suppressed entirely while the auto-tuner is
mid-convergence — a probing tuner moves knobs ON PURPOSE, and PR 13's
rate-signature discipline says its self-inflicted rate swings must
never read as anomalies.

Firing journals a ``telemetry_anomaly`` record (a synthetic
``telemetry/<signal>`` pod key — pod-shaped for the schema, never a
cluster pod, so the completeness invariants ignore it), ticks
``scheduler_anomaly_total{signal}``, and flips :attr:`degraded` — the
hint the scheduler folds into the same degraded flag the fleet
exchange and the resilience breaker already publish.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .. import metrics
from .timeseries import TimeSeriesRing

# signal -> direction ("up" = rising is bad, "down" = falling is bad)
SIGNALS = {
    "pods_per_sec": "down",
    "p99_latency_s": "up",
    "chain_fraction": "down",
    "discard_rate": "up",
    "cas_conflict_rate": "up",
    "gang_incomplete_rate": "up",
    "breaker": "edge",
}

# near-zero-baseline rates: a spike/drift ratio over ~0 is noise, so
# these additionally need an absolute per-window event floor to fire
_EVENT_FLOOR = ("discard_rate", "cas_conflict_rate", "gang_incomplete_rate")


@dataclass(frozen=True)
class Anomaly:
    signal: str
    kind: str  # spike | drift | edge
    value: float
    baseline: float
    window_seq: int

    def describe(self) -> str:
        return (
            f"{self.signal} {self.kind}: value={self.value:.4f} "
            f"baseline={self.baseline:.4f} window={self.window_seq}"
        )


@dataclass
class SentinelConfig:
    # batches aggregated per window sample (the ring's granularity)
    window_batches: int = 8
    # fast/slow window widths, in samples
    fast_windows: int = 3
    slow_windows: int = 24
    # fast-vs-slow regression ratio that arms the spike rule
    spike_ratio: float = 2.0
    # slow-vs-previous-slow ratio that fires the drift rule
    drift_ratio: float = 1.5
    # consecutive regressed windows before a spike fires (hysteresis)
    hysteresis: int = 2
    # windows a fired signal stays silent before it can fire again
    cooldown_windows: int = 12
    # ring warmup: no rule evaluates before this many samples exist
    min_windows: int = 6
    # absolute per-window event floor for the near-zero-baseline rates
    min_events: float = 3.0
    # windows of clean samples before the degraded hint clears
    recover_windows: int = 6
    ring_capacity: int = 256

    def validate(self) -> None:
        if self.window_batches < 1:
            raise ValueError("sentinel.window_batches must be >= 1")
        if self.fast_windows < 1 or self.slow_windows < self.fast_windows:
            raise ValueError(
                "sentinel windows must satisfy 1 <= fast <= slow"
            )
        if self.spike_ratio <= 1.0 or self.drift_ratio <= 1.0:
            raise ValueError("sentinel ratios must be > 1.0")


class AnomalySentinel:
    """Evaluates the regression rules each time a window sample lands.

    Driver-thread only (rides the same commit seam as the SLO engine);
    ``snapshot`` is safe from any thread (the ring locks internally,
    the scalars are read racily but atomically).
    """

    def __init__(self, config: SentinelConfig | None = None) -> None:
        self.config = config or SentinelConfig()
        self.config.validate()
        self.ring = TimeSeriesRing(self.config.ring_capacity)
        # consecutive regressed windows per signal (the hysteresis arm)
        self._streak: dict[str, int] = {}
        # window seq until which a fired signal stays silent
        self._cooldown_until: dict[str, int] = {}
        self._clean_since_fire = 0
        self.fired: list[Anomaly] = []
        self.fired_total = 0
        self.degraded = False
        self.suppressed_windows = 0

    # -- the per-window evaluation --

    def observe_window(
        self, sample, *, suppress: bool = False
    ) -> list[Anomaly]:
        """Evaluate every rule against the ring (``sample`` is the
        window just appended). ``suppress`` skips the regression rules
        (tuner mid-probe) — edges still fire: a breaker trip is never
        the tuner's doing."""
        cfg = self.config
        out: list[Anomaly] = []
        seq = sample.seq
        # edge signals first: discrete events, no baseline needed
        if sample.signals.get("breaker", 0.0) > 0.0 and self._armed(
            "breaker", seq
        ):
            out.append(
                Anomaly(
                    signal="breaker", kind="edge",
                    value=sample.signals["breaker"], baseline=0.0,
                    window_seq=seq,
                )
            )
        if suppress:
            self.suppressed_windows += 1
            self._streak.clear()
        elif len(self.ring) >= cfg.min_windows:
            for signal, direction in SIGNALS.items():
                if direction == "edge":
                    continue
                a = self._evaluate(signal, direction, sample, seq)
                if a is not None:
                    out.append(a)
        for a in out:
            self._cooldown_until[a.signal] = seq + cfg.cooldown_windows
            self._streak.pop(a.signal, None)
            self.fired.append(a)
            self.fired_total += 1
            metrics.anomaly_total.labels(a.signal).inc()
        if len(self.fired) > 64:
            del self.fired[:-64]
        if out:
            self.degraded = True
            self._clean_since_fire = 0
        elif self.degraded:
            self._clean_since_fire += 1
            if self._clean_since_fire >= cfg.recover_windows:
                self.degraded = False
        return out

    def _armed(self, signal: str, seq: int) -> bool:
        return seq >= self._cooldown_until.get(signal, 0)

    def _regressed(self, direction: str, value: float, base: float,
                   ratio: float) -> bool:
        if direction == "up":
            return value >= base * ratio and value > 0.0
        # "down": a collapse against a meaningful baseline
        return base > 0.0 and value * ratio <= base

    def _evaluate(self, signal, direction, sample, seq) -> Anomaly | None:
        cfg = self.config
        if not self._armed(signal, seq):
            return None
        value = sample.signals.get(signal, 0.0)
        if signal in _EVENT_FLOOR and value < cfg.min_events:
            self._streak.pop(signal, None)
            return None
        fast = self.ring.mean(signal, cfg.fast_windows)
        slow_base = self.ring.mean_prev(
            signal, cfg.slow_windows, skip=cfg.fast_windows
        )
        if self._regressed(direction, fast, slow_base, cfg.spike_ratio):
            streak = self._streak.get(signal, 0) + 1
            self._streak[signal] = streak
            if streak >= cfg.hysteresis:
                return Anomaly(
                    signal=signal, kind="spike", value=fast,
                    baseline=slow_base, window_seq=seq,
                )
            return None
        self._streak.pop(signal, None)
        # drift: two adjacent slow windows (needs 2x slow of history)
        if len(self.ring) >= 2 * cfg.slow_windows:
            slow = self.ring.mean(signal, cfg.slow_windows)
            prev = self.ring.mean_prev(
                signal, cfg.slow_windows, skip=cfg.slow_windows
            )
            if self._regressed(direction, slow, prev, cfg.drift_ratio):
                return Anomaly(
                    signal=signal, kind="drift", value=slow,
                    baseline=prev, window_seq=seq,
                )
        return None

    # -- surfaces --

    def snapshot(self) -> dict:
        return {
            "degraded": self.degraded,
            "fired_total": self.fired_total,
            "suppressed_windows": self.suppressed_windows,
            "recent_anomalies": [
                {
                    "signal": a.signal,
                    "kind": a.kind,
                    "value": round(a.value, 6),
                    "baseline": round(a.baseline, 6),
                    "window": a.window_seq,
                }
                for a in self.fired[-16:]
            ],
            "windows": self.ring.snapshot(16),
        }


@dataclass(frozen=True)
class SyntheticPod:
    """Pod-shaped carrier for non-pod journal records: the
    ``telemetry_anomaly`` outcome attaches to ``telemetry/<signal>``,
    a key no cluster pod can have (pod names can't contain ``/`` twice
    under the ``ns/name`` convention), so journal-completeness
    invariants — which iterate real cluster pods — never see it."""

    key: str
    uid: str = ""
    name: str = ""
    namespace: str = ""
