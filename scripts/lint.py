#!/usr/bin/env python
"""Repo lint gate: run the tracer-safety & lock-discipline analyzer.

Thin wrapper over ``python -m kubernetes_tpu.analysis`` so CI and
pre-commit hooks have one entry point; exits non-zero on any
unsuppressed finding. Extra arguments pass through (e.g. ``--json``,
or specific paths to scan).

    python scripts/lint.py
    python scripts/lint.py --json kubernetes_tpu/scheduler.py
"""

from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parents[1]))

from kubernetes_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
