"""Degraded-mode solve resilience: the fallback ladder, the per-profile
circuit breaker, and pre-apply output validation.

The batched device solve is the scheduler's single point of failure: a
device/runtime error, a poison pod that breaks tensorize/solve, or a
silently-corrupt result would otherwise kill the whole batch — and in
fleet mode blackhole a replica's entire shard. This module makes the
scheduler *always make forward progress*, at the best tier the hardware
currently allows:

- **Fallback ladder** (``build_ladder``): sharded-mesh solve →
  single-device solve → CPU-backed exact solve → pure-host serial
  greedy (``host_greedy_assign``, reusing the ``ops/oracle`` pipeline).
  The last rung is plain Python over host state and cannot be taken
  down by the accelerator, which is what makes "always forward
  progress" a guarantee instead of a hope. Tiers that do not exist in
  the current environment (no mesh configured, already running on the
  CPU backend) are omitted.
- **Circuit breaker** (``SolveResilience``): each device tier carries a
  breaker. A solve failure triggers ONE session rebuild and a retry at
  the same tier (device-session loss heals without descending); a
  failure of the rebuilt retry is a deterministic episode that trips
  the breaker — the scheduler descends one rung and keeps serving.
  Tripped breakers re-open for a single PROBE solve after their fault
  window (exponential backoff on repeated trips); a probe success
  re-closes the breaker and the scheduler climbs back up.
- **Pre-apply output validation** (``validate_assignments``): the
  already-materialized host tensors are enough to prove an assignment
  vector sane — integer dtype, node ids in range, only live snapshot
  slots, and no per-node overcommit against the batch's tensorize-time
  capacity (accumulated across a chained sub-batch split). A corrupt
  solve is treated as a solve FAILURE feeding the breaker; it is never
  applied.

Failures that survive the whole ladder (the host rung fails too, or
tensorize itself dies) are data-shaped, not hardware-shaped: the
scheduler bisects the batch to the offending pod(s) and quarantines
them (``Scheduler._bisect_or_quarantine``) with a terminal
``quarantined`` journal outcome and a TTL'd backoff re-admit, while the
rest of the batch proceeds.

Determinism contract: all timing comes off the injectable ``Clock``,
state transitions are pure functions of the (deterministic) failure
sequence, and the host greedy rung breaks ties by lowest node index —
two same-seed simulator runs stay byte-identical
(``sim/README.md``, the ``solver_flaky`` / ``poison_pods`` profiles).
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from . import metrics

# ladder tiers, best first (build_ladder trims to what exists)
TIER_MESH = "mesh"  # node-axis GSPMD solve over the full mesh
TIER_SINGLE = "single"  # same exact solver, one device
TIER_CPU = "cpu"  # same exact solver, forced onto the CPU backend
TIER_HOST = "host"  # pure-host serial greedy (ops/oracle), no jax

# breaker states for the scheduler_tpu_breaker_state gauge
STATE_CLOSED = 0
STATE_OPEN = 1
STATE_HALF_OPEN = 2

# actions on_failure hands back to the scheduler's resilient solve loop
ACT_REBUILD = "rebuild"  # reset the device session, retry the same tier
ACT_RETRY = "retry"  # episode recorded, threshold not reached: same tier
ACT_DESCEND = "descend"  # breaker tripped: re-acquire (one rung lower)
ACT_BISECT = "bisect"  # the last rung failed: data-shaped, bisect


class SolverFaultError(Exception):
    """A solve-boundary failure the resilience layer owns: injected sim
    faults, read failures, and corrupt outputs all subclass or raise
    this family so the scheduler can distinguish them from plugin /
    binding exceptions (which keep their existing semantics)."""


class SolveCorruptError(SolverFaultError):
    """Pre-apply validation rejected the solve's output: the result is
    treated as a failed solve (feeding the breaker), never applied."""


class SolverReadError(SolverFaultError):
    """The deferred device→host assignment read itself died (session /
    transfer loss after dispatch)."""


def build_ladder(have_mesh: bool) -> tuple[str, ...]:
    """The fallback tiers that actually exist in this environment, best
    first. ``TIER_CPU`` is only a distinct rung when the default jax
    backend is NOT already the CPU (otherwise single-device == CPU and
    a duplicate rung would just slow the descent)."""
    tiers = []
    if have_mesh:
        tiers.append(TIER_MESH)
    tiers.append(TIER_SINGLE)
    try:
        import jax

        if jax.default_backend() != "cpu":
            tiers.append(TIER_CPU)
    except Exception:  # pragma: no cover - jax always importable here
        pass
    tiers.append(TIER_HOST)
    return tuple(tiers)


def cpu_device():
    """The host-platform device for the TIER_CPU rung (jax.default_device
    context target). None when the platform has no distinct CPU device."""
    import jax

    try:
        return jax.local_devices(backend="cpu")[0]
    except Exception:  # pragma: no cover - cpu backend always present
        return None


def tier_device_context(tier: str):
    """Context manager pinning a TIER_CPU dispatch onto the CPU backend
    (the accelerator runtime is sick but the host still computes the
    same exact solve); a no-op for every other tier."""
    import contextlib

    if tier != TIER_CPU:
        return contextlib.nullcontext()
    dev = cpu_device()
    if dev is None:  # pragma: no cover - cpu backend always present
        return contextlib.nullcontext()
    import jax

    return jax.default_device(dev)


@dataclass
class ResilienceConfig:
    """SchedulerConfig.resilience: knobs for the fallback ladder, the
    per-profile circuit breaker, and the poison-batch quarantine."""

    # breaker: deterministic failure EPISODES (fail → session rebuild →
    # fail again) at one tier before its breaker trips
    trip_after: int = 1
    # fault window: how long a tripped breaker stays open before it
    # half-opens for a single probe solve
    open_seconds: float = 30.0
    # repeated trips of the same tier back off the window exponentially
    open_backoff: float = 2.0
    max_open_seconds: float = 600.0
    # quarantine: how long a poison pod sits out before re-admission,
    # with exponential backoff on repeated quarantines
    quarantine_ttl: float = 60.0
    quarantine_backoff: float = 2.0
    max_quarantine_ttl: float = 900.0
    # pin the ladder to one tier (bench ladder #9's forced host-greedy
    # arm; tests). The breaker machinery is bypassed entirely.
    force_tier: str | None = None
    # master switch for pre-apply output validation (the ladder itself
    # has no switch: with no failures it is zero-cost)
    validate: bool = True


class _ProfileState:
    """Per-profile breaker ladder state (driver thread only)."""

    __slots__ = (
        "rebuilt", "episode_fails", "open_until", "open_count",
        "probing", "async_fail",
    )

    def __init__(self) -> None:
        self.rebuilt = False  # session rebuild already spent this episode
        self.episode_fails: dict[int, int] = {}  # tier idx -> episodes
        self.open_until: dict[int, float] = {}  # tier idx -> half-open at
        self.open_count: dict[int, int] = {}  # tier idx -> trips (backoff)
        self.probing: int | None = None  # tier idx under probe
        self.async_fail = False  # a deferred solve failed post-dispatch


class SolveResilience:
    """The fallback ladder + circuit breaker state machine, one ladder
    per scheduler profile. Driver-thread only (both scheduling loops are
    single-driver); the scheduler consults it around every dispatch.

    State machine per device tier (host has no breaker):

        closed ──(trip_after deterministic episodes)──► open
        open   ──(fault window elapses; next acquire)──► half-open (probe)
        half-open ──(probe succeeds)──► closed
        half-open ──(probe fails)────► open (window × backoff)

    The CURRENT tier is always the best rung without an open breaker;
    probes temporarily run one failed rung for a single solve.
    """

    def __init__(
        self,
        config: ResilienceConfig | None,
        clock,
        ladder: tuple[str, ...],
        on_degraded=None,
    ) -> None:
        self.config = config or ResilienceConfig()
        self.clock = clock
        self.ladder = ladder
        # fleet hook: called with True when the first breaker trips and
        # False when the last one re-closes (the occupancy exchange's
        # degraded flag, so peers route refugees elsewhere)
        self.on_degraded = on_degraded
        # SLO health signal (obs/slo.py, wired by the Scheduler): while
        # the error budget burns past the degraded threshold, half-open
        # breaker probes are DEFERRED — the rung under probe already
        # failed once, and re-probing it while users are actively
        # missing their SLO risks another failed batch exactly when it
        # hurts most. The currently-working rung keeps serving; probes
        # resume (and re-close can complete) once health returns.
        self.slo_degraded = False
        self._state: dict[str, _ProfileState] = {}
        # python-side counters: the sim footer reads these (reading the
        # shared metrics registry would leak cross-run state)
        self.trips = 0
        self.recloses = 0
        self.probes = 0
        self.probes_deferred = 0  # probes skipped while SLO-degraded
        self.rebuilds = 0
        if self.config.force_tier is not None and (
            self.config.force_tier not in ladder
        ):
            raise ValueError(
                f"force_tier {self.config.force_tier!r} is not in the "
                f"ladder {ladder}"
            )

    def _st(self, profile: str) -> _ProfileState:
        st = self._state.get(profile)
        if st is None:
            st = self._state[profile] = _ProfileState()
            metrics.solve_tier.labels(profile).set(0)
            metrics.breaker_state.labels(profile).set(STATE_CLOSED)
        return st

    # -- tier selection --

    def acquire(self, profile: str) -> tuple[int, str]:
        """The (tier index, tier name) the next solve attempt should
        run at: the best rung without an open breaker, or — when a
        tripped rung's fault window has elapsed — that rung as a
        single half-open probe."""
        if self.config.force_tier is not None:
            idx = self.ladder.index(self.config.force_tier)
            return idx, self.config.force_tier
        st = self._st(profile)
        now = self.clock.now()
        for idx in range(len(self.ladder)):
            until = st.open_until.get(idx)
            if until is None:
                metrics.solve_tier.labels(profile).set(idx)
                return idx, self.ladder[idx]
            if now >= until:
                if self.slo_degraded:
                    # SLO consumption: the fault window elapsed, but
                    # the error budget is burning — keep serving at
                    # the rung that works and defer the probe until
                    # health returns
                    self.probes_deferred += 1
                    continue
                # half-open: one probe at the failed rung
                st.probing = idx
                self.probes += 1
                metrics.breaker_state.labels(profile).set(STATE_HALF_OPEN)
                metrics.breaker_transitions_total.labels("probe").inc()
                metrics.solve_tier.labels(profile).set(idx)
                return idx, self.ladder[idx]
        # unreachable: the host rung never opens a breaker
        idx = len(self.ladder) - 1  # pragma: no cover
        return idx, self.ladder[idx]  # pragma: no cover

    def on_success(self, profile: str, tier_idx: int) -> None:
        """A solve at ``tier_idx`` completed and validated: close its
        breaker if it was probing, and reset the episode bookkeeping.
        Success at a LOWER rung says nothing about the rungs above —
        their windows keep counting down toward their own probes."""
        st = self._st(profile)
        st.rebuilt = False
        st.async_fail = False
        st.episode_fails.pop(tier_idx, None)
        was_degraded = bool(st.open_until)
        if st.probing == tier_idx or tier_idx in st.open_until:
            st.open_until.pop(tier_idx, None)
            st.open_count.pop(tier_idx, None)
            self.recloses += 1
            metrics.breaker_transitions_total.labels("reclose").inc()
        st.probing = None
        metrics.breaker_state.labels(profile).set(
            STATE_OPEN if st.open_until else STATE_CLOSED
        )
        if was_degraded and not st.open_until and self.on_degraded:
            self.on_degraded(False)

    def on_failure(self, profile: str, tier_idx: int) -> str:
        """A solve at ``tier_idx`` failed. Returns the action for the
        scheduler's resilient solve loop (ACT_*)."""
        st = self._st(profile)
        if self.ladder[tier_idx] == TIER_HOST:
            # the last rung failed: this is not a hardware problem
            st.rebuilt = False
            return ACT_BISECT
        if self.config.force_tier is not None:
            # the ladder is pinned: there is no rung to descend to, and
            # looping REBUILD/DESCEND back into the same forced tier
            # would livelock on a deterministic failure. One session
            # rebuild, then treat it as data-shaped (bisect/quarantine
            # terminates).
            if not st.rebuilt:
                st.rebuilt = True
                self.rebuilds += 1
                metrics.breaker_transitions_total.labels("rebuild").inc()
                return ACT_REBUILD
            st.rebuilt = False
            return ACT_BISECT
        if st.probing == tier_idx:
            # probe failed: re-open with backoff, fall back down
            st.probing = None
            self._open(profile, st, tier_idx)
            return ACT_DESCEND
        if not st.rebuilt:
            # device-session loss heals with one rebuild before the
            # breaker is charged
            st.rebuilt = True
            self.rebuilds += 1
            metrics.breaker_transitions_total.labels("rebuild").inc()
            return ACT_REBUILD
        # the rebuilt retry failed too: a deterministic episode
        st.rebuilt = False
        fails = st.episode_fails.get(tier_idx, 0) + 1
        st.episode_fails[tier_idx] = fails
        if fails < self.config.trip_after:
            return ACT_RETRY
        st.episode_fails.pop(tier_idx, None)
        self._open(profile, st, tier_idx)
        return ACT_DESCEND

    def _open(self, profile: str, st: _ProfileState, tier_idx: int) -> None:
        was_degraded = bool(st.open_until)
        trips = st.open_count.get(tier_idx, 0) + 1
        st.open_count[tier_idx] = trips
        window = min(
            self.config.open_seconds
            * self.config.open_backoff ** (trips - 1),
            self.config.max_open_seconds,
        )
        st.open_until[tier_idx] = self.clock.now() + window
        self.trips += 1
        metrics.breaker_state.labels(profile).set(STATE_OPEN)
        metrics.breaker_transitions_total.labels("trip").inc()
        if not was_degraded and self.on_degraded:
            self.on_degraded(True)

    # -- pipelined-loop integration --

    def note_async_failure(self, profile: str) -> None:
        """A deferred solve failed after dispatch (read error / corrupt
        output): route the retry through the synchronous resilient path
        (``should_sync``), where the ladder can handle it."""
        self._st(profile).async_fail = True

    def should_sync(self) -> bool:
        """True when the pipelined loop must route popped batches
        through the synchronous resilient cycle: a tier is degraded or
        probing, an async failure is pending, or the ladder is pinned."""
        if self.config.force_tier is not None:
            return True
        return any(
            st.async_fail or st.open_until
            for st in self._state.values()
        )

    # -- introspection (sim footer / metrics / tests) --

    def tier_index(self, profile: str) -> int:
        """The rung the NEXT solve will run at: the best tier whose
        breaker is closed or whose fault window has already elapsed
        (the next solve probes it — from the caller's perspective the
        scheduler is back at that tier)."""
        if self.config.force_tier is not None:
            return self.ladder.index(self.config.force_tier)
        st = self._st(profile)
        now = self.clock.now()
        for idx in range(len(self.ladder)):
            until = st.open_until.get(idx)
            if until is None or now >= until:
                return idx
        return len(self.ladder) - 1  # pragma: no cover

    def summary(self) -> dict:
        """Deterministic state snapshot for the sim's trace footer.
        The current tier reports as ``"top"`` at depth 0 rather than by
        name: the ladder's SHAPE depends on the environment (mesh
        devices, backend), and naming the healthy top tier would break
        the sim's trace device-count-invariance contract — a fault-free
        run's footer must be byte-identical at any mesh size."""
        per_profile = {}
        for name, st in sorted(self._state.items()):
            depth = self.tier_index(name)
            per_profile[name] = {
                "tier": "top" if depth == 0 else self.ladder[depth],
                "open": sorted(self.ladder[i] for i in st.open_until),
            }
        return {
            "trips": self.trips,
            "recloses": self.recloses,
            "probes": self.probes,
            "probes_deferred": self.probes_deferred,
            "rebuilds": self.rebuilds,
            "profiles": per_profile,
        }

    # -- SLO health consumption (obs/slo.py, wired by the Scheduler) --

    def set_slo_degraded(self, degraded: bool) -> None:
        """While set, ``acquire`` defers half-open probes: don't re-try
        the rung that already failed while the error budget is
        actively burning — the working rung keeps serving, the probe
        (and its re-close) runs once health returns."""
        self.slo_degraded = bool(degraded)


# -- pre-apply output validation --


def validate_assignments(
    prep, lo: int, assignments, disabled: frozenset = frozenset()
) -> str | None:
    """Validate one flight's assignment vector against the group's
    already-materialized host tensors BEFORE any of it is applied.
    Returns a reason string (→ the solve is treated as failed and feeds
    the breaker) or None.

    Checks: integer dtype and shape, node ids in [-1, padded), assigned
    slots live in the snapshot (named + valid), and no per-node
    overcommit against tensorize-time capacity — accumulated across the
    chained sub-flights of one prepared group via
    ``prep.validated_usage``, mirroring the device-side
    ``BatchCarriedUsage`` carry. The capacity check is conservative in
    the lenient direction only: events between tensorize and apply can
    FREE capacity (assigned-pod deletes), never consume it unseen
    (capacity-consuming events bump the conflict fence and discard the
    flight first), so a flagged overcommit is always a corrupt solve.
    ``disabled``: the profile's disabled Filter plugins — with
    "NodeResourcesFit" disabled, overcommit is LEGAL solver output and
    the capacity half is skipped (the structural checks still run).

    Gang note: a pod group solved as one chained sub-batch flows
    through here one sub-flight at a time like any other chain —
    ``prep.validated_usage`` already carries usage across the gang's
    sub-flights, so a corrupt solve for a later member is caught
    against the load of earlier members the same gang round staged.
    """
    a = np.asarray(assignments)
    if a.ndim != 1:
        return f"assignment vector has {a.ndim} dims, expected 1"
    if not np.issubdtype(a.dtype, np.integer):
        return f"assignment dtype {a.dtype} is not an integer type"
    if a.size == 0:
        return None
    batch = prep.batch
    lo_v = int(a.min())
    hi_v = int(a.max())
    if lo_v < -1 or hi_v >= batch.padded:
        return (
            f"node id out of range: [{lo_v}, {hi_v}] vs "
            f"[-1, {batch.padded})"
        )
    assigned = np.nonzero(a >= 0)[0]
    if assigned.size == 0:
        return None
    slots = a[assigned].astype(np.int64)
    # per-node overcommit across this prep's flights (chained sub-
    # batches share one tensorize; the accumulator is the host mirror
    # of the device-resident carry). The named-slot table is built once
    # per prep alongside it.
    acc = prep.validated_usage
    if acc is None:
        named = np.zeros(batch.padded, dtype=bool)
        for si, name in enumerate(prep.names[: batch.padded]):
            named[si] = bool(name)
        acc = prep.validated_usage = {
            "used": np.zeros_like(batch.used),
            "count": np.zeros_like(batch.pod_count),
            "named": named,
        }
    if not bool(batch.valid[slots].all()):
        bad = int(slots[~batch.valid[slots]][0])
        return f"assignment targets invalid snapshot slot {bad}"
    if not bool(acc["named"][slots].all()):
        bad = int(slots[~acc["named"][slots]][0])
        return f"assignment targets unnamed snapshot slot {bad}"
    if "NodeResourcesFit" in disabled:
        # the profile legalized overcommit: only structural checks apply
        return None
    req = np.maximum(prep.pbatch.req[lo + assigned], 0)  # [m, K]
    # deltas are checked BEFORE merging into the accumulator: a failed
    # validation must not pollute the ladder-rung retry of the same
    # prep with phantom usage (the retry's correct output would then
    # falsely flag overcommit at every rung)
    uniq, inv = np.unique(slots, return_inverse=True)
    d_used = np.zeros((batch.used.shape[0], uniq.size), batch.used.dtype)
    np.add.at(d_used.T, inv, req)
    d_count = np.bincount(inv, minlength=uniq.size).astype(
        batch.pod_count.dtype
    )
    total = batch.used[:, uniq] + acc["used"][:, uniq] + d_used
    if bool((total > batch.allocatable[:, uniq]).any()):
        over = uniq[
            (total > batch.allocatable[:, uniq]).any(axis=0)
        ]
        return (
            "per-node overcommit on snapshot slot(s) "
            f"{[int(s) for s in over[:4]]}"
        )
    counts = batch.pod_count[uniq] + acc["count"][uniq] + d_count
    if bool((counts > batch.max_pods[uniq]).any()):
        return "per-node pod-count overcommit"
    acc["used"][:, uniq] += d_used
    acc["count"][uniq] += d_count
    return None


# -- the pure-host last rung --


def host_greedy_assign(prep, placed_by_slot, solver_config) -> np.ndarray:
    """The ladder's last rung: the reference's sequential scheduleOne
    loop in plain Python (``ops/oracle/profile.FullOracle``) over the
    group's already-materialized host state — zero accelerator surface.

    Filters: the full scalar oracle pipeline (fit, ports, spread,
    interpod, volumes, taints/affinity/selectors) AND the group's
    folded static class mask, so out-of-tree plugin / extender / DRA
    verdicts folded at tensorize time still hold. Scoring: the default
    profile weights with first-index tie-break (deterministic).
    Nominated-pod load is not modeled — this is the emergency rung;
    placements are valid, not nomination-optimal. Returns snapshot-slot
    assignments shaped exactly like the device solve's, so the apply
    path downstream is identical."""
    from .ops.oracle.profile import FullOracle, make_oracle_nodes

    live = [
        (slot, node)
        for slot, node in enumerate(prep.slot_nodes)
        if node is not None
    ]
    by_name = {
        node.name: list(placed_by_slot.get(slot, ()))
        for slot, node in live
    }
    oracle = FullOracle(
        make_oracle_nodes([node for _, node in live], by_name),
        volume_ctx=prep.volume_ctx,
        services=prep.services,
        spread_defaulting=solver_config.spread_defaulting,
        disabled=frozenset(solver_config.disabled_filters),
    )
    mask = np.asarray(prep.static.mask)
    class_of = np.asarray(prep.static.class_of)
    slot_of = [slot for slot, _ in live]
    out = np.full(len(prep.pods), -1, dtype=np.int32)
    for i, pod in enumerate(prep.pods):
        row = mask[int(class_of[i])]
        feasible = [
            j for j in oracle.feasible_set(pod) if row[slot_of[j]]
        ]
        if not feasible:
            continue
        totals = oracle.score_totals(pod, feasible)
        best = max(totals[j] for j in feasible)
        pick = next(j for j in feasible if totals[j] == best)
        oracle.nodes[pick].add_pod(pod)
        out[i] = slot_of[pick]
    return out
