"""CLI: explain a pod's scheduling history / validate a trace file.

    # from a recorded journal or flight-recorder dump
    python -m kubernetes_tpu.obs explain default/pod-3 --trace journal.jsonl
    python -m kubernetes_tpu.obs explain <pod-uid> --trace dump.jsonl

    # from a live scheduler's flight recorder (serve --mode scheduler)
    python -m kubernetes_tpu.obs explain pod-3 --url http://127.0.0.1:10259

    # cross-replica fleet history: merge several replicas' journals
    # (repeat --trace per file, point --trace at the hub's aggregated
    # journal, or pull it live with --hub) and order records by the
    # PR 8 fleet merge/tie-break rules
    python -m kubernetes_tpu.obs explain pod-3 --fleet \\
        --trace hub_journal.jsonl
    python -m kubernetes_tpu.obs explain pod-3 --fleet \\
        --trace r0.jsonl --trace r1.jsonl
    python -m kubernetes_tpu.obs explain pod-3 --fleet --hub 127.0.0.1:50051

    # schema-check a journal / dump (the CI obs smoke)
    python -m kubernetes_tpu.obs validate journal.jsonl

    # live per-stage profile + anomaly sentinel (serve --telemetry)
    python -m kubernetes_tpu.obs top --url http://127.0.0.1:10259

    # re-execute a capture-on-anomaly bundle, assert bit-identical
    # assignments (the CI telemetry smoke)
    python -m kubernetes_tpu.obs replay /var/bundles/bundle-00000-sentinel

Exit status: 0 found/valid; 1 pod not found or schema errors; 2 usage.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path


def _load_lines(args) -> list[str]:
    lines: list[str] = []
    for trace in args.trace or []:
        lines.extend(Path(trace).read_text().splitlines())
    if getattr(args, "hub", None):
        # the occupancy hub's append-only journal aggregation surface
        # (fleet/occupancy.py ship_journal): replicas piggyback bounded
        # journal segments on their write-behind flushes; one HubOp
        # read returns the merged lines
        from ..server.bulk import BulkClient

        client = BulkClient(args.hub, retries=0)
        try:
            lines.extend(client.hub_op("journal_lines")["lines"] or [])
        finally:
            client.close()
    if args.url:
        import json
        import urllib.request

        from .recorder import canonical

        url = args.url.rstrip("/") + "/debug/flightrecorder"
        with urllib.request.urlopen(url, timeout=10.0) as r:
            doc = json.loads(r.read().decode())
        lines.extend(
            [canonical(rec) for rec in doc.get("decisions") or []]
            + [canonical(sp) for sp in doc.get("spans") or []]
        )
    if not lines and not (args.trace or args.url or getattr(args, "hub", None)):
        raise SystemExit(
            "error: one of --trace, --url, or --hub is required"
        )
    return lines


def cmd_explain(args) -> int:
    from .explain import explain_pod, parse_stream

    decisions, spans = parse_stream(_load_lines(args))
    out = explain_pod(decisions, args.pod, spans=spans, fleet=args.fleet)
    print(out.render())
    return 0 if out.found else 1


def cmd_validate(args) -> int:
    from .journal import validate_lines

    lines = Path(args.trace).read_text().splitlines()
    errors = validate_lines(lines)
    for err in errors:
        print(f"{args.trace}: {err}", file=sys.stderr)
    n = sum(1 for ln in lines if ln.strip())
    if errors:
        print(f"{args.trace}: {len(errors)} schema error(s) in {n} record(s)")
        return 1
    print(f"{args.trace}: {n} record(s), schema OK")
    return 0


def cmd_top(args) -> int:
    from .profile import render_top

    import json

    if args.snapshot:
        doc = json.loads(Path(args.snapshot).read_text())
    else:
        import urllib.request

        url = args.url.rstrip("/") + "/debug/profile"
        if args.capture:
            url += "?capture=1"
        try:
            with urllib.request.urlopen(url, timeout=10.0) as r:
                doc = json.loads(r.read().decode())
        except OSError as e:
            print(f"error: {url}: {e}", file=sys.stderr)
            return 1
    if doc.get("error"):
        print(f"error: {doc['error']}", file=sys.stderr)
        return 1
    print(render_top(doc))
    return 0


def cmd_replay(args) -> int:
    """Re-execute a captured bundle's solve offline and compare
    against the recorded assignments. Exit 0 = bit-identical, 1 =
    diverged (the forensic artifact lies — a real bug), 3 = the solve
    was structurally non-replayable standalone (chained/split)."""
    from .bundle import replay_bundle

    try:
        rep = replay_bundle(args.bundle)
    except (OSError, ValueError, KeyError) as e:
        print(f"error: {args.bundle}: {e}", file=sys.stderr)
        return 1
    print(
        f"{args.bundle}: replayable={rep['replayable']} "
        f"pods={rep['pods']} parts={rep['parts']}"
    )
    print(f"  {rep['detail']}")
    if not rep["replayable"]:
        return 3
    return 0 if rep["ok"] else 1


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m kubernetes_tpu.obs",
        description="Scheduling-trace tools: explain pods, validate "
        "traces, watch the live stage profile, replay capture bundles.",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_explain = sub.add_parser(
        "explain", help="reconstruct one pod's scheduling history"
    )
    p_explain.add_argument(
        "pod", help="pod uid, ns/name key, or bare pod name"
    )
    p_explain.add_argument(
        "--trace", metavar="FILE", action="append",
        help="journal / flight-recorder JSONL to read (repeatable: "
        "--fleet merges several replicas' journals)",
    )
    p_explain.add_argument(
        "--url", metavar="URL",
        help="base URL of a live scheduler (reads /debug/flightrecorder)",
    )
    p_explain.add_argument(
        "--fleet", action="store_true",
        help="cross-replica mode: merge records from every input "
        "journal with the PR 8 fleet merge/tie-break rules and render "
        "the handoff chain (replicas traversed, one journey trace)",
    )
    p_explain.add_argument(
        "--hub", metavar="HOST:PORT",
        help="bulk gRPC address of a fleet occupancy hub: read its "
        "aggregated journal surface (replicas ship bounded segments "
        "piggybacked on their write-behind flushes)",
    )
    p_explain.set_defaults(fn=cmd_explain)

    p_val = sub.add_parser(
        "validate", help="schema-check a journal / flight-recorder JSONL"
    )
    p_val.add_argument("trace", metavar="FILE")
    p_val.set_defaults(fn=cmd_validate)

    p_top = sub.add_parser(
        "top",
        help="render a live scheduler's per-stage profile + sentinel "
        "state (reads GET /debug/profile; serve --telemetry)",
    )
    p_top.add_argument(
        "--url", metavar="URL", default="http://127.0.0.1:10259",
        help="base URL of a live scheduler (default %(default)s)",
    )
    p_top.add_argument(
        "--snapshot", metavar="FILE",
        help="render a saved /debug/profile JSON document instead of "
        "fetching one (offline forensics)",
    )
    p_top.add_argument(
        "--capture", action="store_true",
        help="also trigger a manual replay-bundle capture (?capture=1)",
    )
    p_top.set_defaults(fn=cmd_top)

    p_replay = sub.add_parser(
        "replay",
        help="re-execute a capture-on-anomaly bundle offline and "
        "assert bit-identical assignments (exit 0 identical, 1 "
        "diverged, 3 not standalone-replayable)",
    )
    p_replay.add_argument(
        "bundle", metavar="DIR",
        help="bundle directory (bundle-NNNNN-<trigger>/)",
    )
    p_replay.set_defaults(fn=cmd_replay)

    args = parser.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
