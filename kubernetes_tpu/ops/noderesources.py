"""JAX kernels for the noderesources plugins: Fit (filter) + scoring
strategies (LeastAllocated / MostAllocated / RequestedToCapacityRatio) +
BalancedAllocation.

Reference semantics (kernels must agree with the oracle in
ops/oracle/noderesources.py, which transcribes):
- fit.go#fitsRequest            -> fit_mask
- least_allocated.go            -> least_allocated_score
- most_allocated.go             -> most_allocated_score
- requested_to_capacity_ratio.go-> rtc_score
- balanced_allocation.go        -> balanced_allocation_score

Design notes (TPU-first):
- Node axis is the trailing axis everywhere -> lanes. The per-pod kernels are
  rank-polymorphic over a leading batch axis via vmap (single-shot mode).
- Integer score arithmetic stays in int64/int32 exactly as the reference's
  int64 math (truncating division on non-negative values == floor_divide).
- BalancedAllocation follows the reference into float land; dtype is a knob
  (float64 on CPU tests for bit-parity with the Go float64 oracle, float32
  on TPU — divergence bounded by the final int truncation and covered by
  tie-set parity tests).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .fastmath import floor_div_exact

MAX_NODE_SCORE = 100


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def fit_mask(
    req: jax.Array,  # [K] int
    req_mask: jax.Array,  # [K] bool — resources the pod requests (>0)
    alloc: jax.Array,  # [K, N] int
    used: jax.Array,  # [K, N] int
    pod_count: jax.Array,  # [N] int32
    max_pods: jax.Array,  # [N] int32
) -> jax.Array:  # [N] bool
    """NodeResourcesFit Filter: every requested resource fits, and the node
    has a free pod slot."""
    res_ok = (used + req[:, None] <= alloc) | (~req_mask[:, None])
    count_ok = pod_count + 1 <= max_pods
    return jnp.all(res_ok, axis=0) & count_ok


def scoring_requested(
    nonzero_req: jax.Array,  # [2] int — pod (cpu, mem) with non-zero defaults
    nonzero_used: jax.Array,  # [2, N] int
) -> jax.Array:  # [2, N] int
    """calculateResourceAllocatableRequest for the default cpu/memory scoring
    resources: scoring uses NonZeroRequested, not Requested."""
    return nonzero_used + nonzero_req[:, None]


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def least_allocated_score(
    requested: jax.Array,  # [R, N] int — per scoring resource
    alloc: jax.Array,  # [R, N] int
    weights: jax.Array,  # [R] int
    div=floor_div_exact,
) -> jax.Array:  # [N] int — 0..100
    """(alloc - requested) * 100 // alloc per resource, weighted int mean.

    ``div``: exact int64 floor division, injectable per call site. Every
    current caller evaluates per-step-class shapes ([R, N] / [R, 2N])
    where the float-estimate trick (floor_div_exact, the default) wins;
    jnp.floor_divide is equally exact on these non-negative operands if
    a future bulk-table caller measures better with it."""
    ok = (alloc > 0) & (requested <= alloc)
    per_res = jnp.where(
        ok,
        div((alloc - requested) * MAX_NODE_SCORE, jnp.maximum(alloc, 1)),
        0,
    )
    wsum = jnp.sum(weights)
    return div(
        jnp.sum(per_res * weights[:, None], axis=0), jnp.maximum(wsum, 1)
    )


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def most_allocated_score(
    requested: jax.Array, alloc: jax.Array, weights: jax.Array,
    div=floor_div_exact,
) -> jax.Array:
    ok = (alloc > 0) & (requested <= alloc)
    per_res = jnp.where(
        ok,
        div(requested * MAX_NODE_SCORE, jnp.maximum(alloc, 1)),
        0,
    )
    wsum = jnp.sum(weights)
    return div(
        jnp.sum(per_res * weights[:, None], axis=0), jnp.maximum(wsum, 1)
    )


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def rtc_score(
    requested: jax.Array,  # [R, N] int
    alloc: jax.Array,  # [R, N] int
    weights: jax.Array,  # [R] int
    shape_x: jax.Array,  # [S] int — utilization breakpoints, ascending 0..100
    shape_y: jax.Array,  # [S] int — scores 0..10 at the breakpoints
    div=floor_div_exact,
) -> jax.Array:
    """RequestedToCapacityRatio: piecewise-linear over integer utilization,
    scaled by MaxNodeScore/10 (shape scores are 0..10 like extender
    priorities)."""
    util = jnp.where(
        alloc > 0,
        jnp.minimum(div(requested * 100, jnp.maximum(alloc, 1)), 100),
        0,
    )  # [R, N]

    def trunc_div(a, b):
        # Go int64 division truncates toward zero; jnp // floors. Decreasing
        # shape segments make the numerator negative, where they differ.
        q = div(jnp.abs(a), jnp.maximum(jnp.abs(b), 1))
        return jnp.where((a >= 0) == (b >= 0), q, -q)

    def interp(u):  # u: [R, N] int
        # piecewise integer interpolation identical to the oracle's _piecewise
        y = jnp.full_like(u, shape_y[0])
        for i in range(1, shape_x.shape[0]):
            x0, y0, x1, y1 = shape_x[i - 1], shape_y[i - 1], shape_x[i], shape_y[i]
            seg = y0 + trunc_div((y1 - y0) * (u - x0), x1 - x0)
            y = jnp.where((u >= x0) & (u < x1), seg, y)
        y = jnp.where(u >= shape_x[-1], shape_y[-1], y)
        return y

    per_res = jnp.where(alloc > 0, interp(util) * (MAX_NODE_SCORE // 10), 0)
    wsum = jnp.sum(weights)
    return div(
        jnp.sum(per_res * weights[:, None], axis=0), jnp.maximum(wsum, 1)
    )


# traced-region kernel, called from exact.py's jit scope: ktpu: hot
def balanced_allocation_score(
    requested: jax.Array,  # [R, N] int — scoring resources (default cpu, mem)
    alloc: jax.Array,  # [R, N] int
    fdtype=jnp.float32,
) -> jax.Array:  # [N] int32 — 0..100
    """(1 - std(fractions)) * 100, truncated to int.

    Exactly-two-resources case uses |f0 - f1| / 2 (reference special case);
    >2 uses population standard deviation.
    """
    f = jnp.where(
        alloc > 0,
        requested.astype(fdtype) / jnp.maximum(alloc, 1).astype(fdtype),
        jnp.asarray(1.0, dtype=fdtype),
    )
    f = jnp.minimum(f, 1.0)
    r = requested.shape[0]
    if r == 2:
        std = jnp.abs(f[0] - f[1]) / 2.0
    elif r > 2:
        mean = jnp.mean(f, axis=0)
        std = jnp.sqrt(jnp.mean((f - mean) ** 2, axis=0))
    else:
        std = jnp.zeros(requested.shape[1], dtype=fdtype)
    return ((1.0 - std) * MAX_NODE_SCORE).astype(jnp.int32)
